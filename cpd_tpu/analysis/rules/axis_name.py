"""axis-name: collective axis literals must be bound somewhere in the
module.

``lax.psum(x, "pd")`` inside a mesh whose axes are ``("dp", "tp")``
raises only when the shard_map actually traces — and in test/example
code the bad spelling frequently hides behind a rarely-run config
branch.  EQuARX-class bugs (PAPERS.md) are silent because quantized
collectives don't crash on semantic mistakes; spelling is the one part
we can gate statically.

Scope: module-local.  Axis *bindings* are collected from every mesh
constructor / PartitionSpec in the file; ``lax`` collective calls whose
axis argument is a string (or tuple-of-string) literal must use bound
names.  Modules that bind NO axes (pure library code that takes
``axis_name`` as a parameter) are exempt — the rule only fires where a
mesh is actually declared, so helpers like parallel/dist.py stay quiet.

Repo-specific bindings understood: ``make_mesh(...)`` /
``data_parallel_mesh(...)`` (parallel/mesh.py) always create all five
canonical axes dp/tp/sp/pp/ep (size-1 axes are kept, see make_mesh's
docstring).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import (Finding, ModuleContext, Rule, base_name, call_arg,
                    dotted_name, register, string_literals)

_MESH_CANONICAL = {"dp", "tp", "sp", "pp", "ep"}

# collective -> (positional index of axis arg, keyword name)
_COLLECTIVES = {
    "psum": (1, "axis_name"),
    "pmean": (1, "axis_name"),
    "pmax": (1, "axis_name"),
    "pmin": (1, "axis_name"),
    "ppermute": (1, "axis_name"),
    "pshuffle": (1, "axis_name"),
    "psum_scatter": (1, "axis_name"),
    "all_gather": (1, "axis_name"),
    "all_to_all": (1, "axis_name"),
    "axis_index": (0, "axis_name"),
    "axis_size": (0, "axis_name"),
    # repo collectives with the same contract (parallel/dist.py)
    "broadcast_from": (1, "axis_name"),
    "all_reduce_mean": (1, "axis_name"),
    "pmax_scalar_vector": (1, "axis_name"),
}


def _axis_strings(node: ast.AST) -> list[ast.Constant]:
    """String constants naming axes in an axis argument: a bare literal,
    or a tuple/list of literals.  Anything else (a variable, an
    f-string) is unresolvable -> []."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [el for el in node.elts
                if isinstance(el, ast.Constant)
                and isinstance(el.value, str)]
    return []


def _declared_axes(ctx: ModuleContext) -> set[str]:
    declared: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = base_name(node.func)
        if name in ("make_mesh", "data_parallel_mesh"):
            declared |= _MESH_CANONICAL
        elif name == "Mesh":
            # jax.sharding.Mesh(devices, axis_names)
            axes = call_arg(node, 1, "axis_names")
            if axes is not None:
                declared |= {c.value for c in string_literals(axes)}
        elif dotted_name(node.func) in ("jax.make_mesh", "make_mesh2"):
            axes = call_arg(node, 1, "axis_names")
            if axes is not None:
                declared |= {c.value for c in string_literals(axes)}
        elif name in ("PartitionSpec", "P"):
            declared |= {c.value for c in string_literals(node)}
        elif name in ("shard_map", "pjit"):
            for kw in node.keywords:
                if kw.arg in ("in_specs", "out_specs", "axis_names"):
                    declared |= {c.value
                                 for c in string_literals(kw.value)}
    return declared


@register
class AxisName(Rule):
    id = "axis-name"
    summary = ("collective axis-name literals must match an axis bound "
               "by a mesh/PartitionSpec in the same module")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        declared = _declared_axes(ctx)
        if not declared:
            return  # library module: axes flow in as parameters
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = base_name(node.func)
            spec = _COLLECTIVES.get(name)
            if spec is None:
                continue
            axis_arg = call_arg(node, spec[0], spec[1])
            if axis_arg is None:
                continue
            for const in _axis_strings(axis_arg):
                if const.value not in declared:
                    yield ctx.finding(
                        self.id, const,
                        f"{name}: axis {const.value!r} is not bound by "
                        f"any mesh/PartitionSpec in this module "
                        f"(bound here: {sorted(declared)})")
