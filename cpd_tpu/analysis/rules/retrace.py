"""retrace: jitted-step caches keyed outside StepTable/ladder_step_key.

The PR 5 review bug, now a lint.  The repo's contract for "many jitted
variants of one step" is a ``StepTable`` (or ``utils.cache.LRUCache``)
keyed through ``resilience.precision.ladder_step_key`` — the ONE key
derivation covering every supervisor combination.  The pre-fix CLI code
keyed its table with the bare ``supervisor.mode`` while a
``PrecisionSupervisor`` was also escalating the format: the key missed
the format coordinate, so the table happily served the step traced at
the OLD format after an escalation — a silently-wrong-precision run,
the exact bug class this whole analyzer exists for.

Three shapes flagged:

1. **jit-in-loop** — ``jax.jit(...)`` constructed inside a ``for``/
   ``while`` body with no ``key not in cache`` memoization guard: a
   fresh jit object per iteration re-traces every step (the memoized
   ``if key not in cache: cache[key] = jax.jit(...)`` idiom of
   train/lm.py stays silent).
2. **half-keyed ladder table** — in a scope holding BOTH a
   ``TransportSupervisor`` and a ``PrecisionSupervisor``, subscripting a
   step table with only one supervisor's ``.mode``/``.fmt`` attribute
   instead of ``ladder_step_key(transport, precision)``.
3. **f-string step keys** — subscripting a dict that holds jitted
   callables with an f-string: stringified keys conflate distinct
   configs ("8" == "8") and churn the table under formatting drift;
   route structured tuples through StepTable/LRUCache.
4. **overlap-blind ladder keys** (ISSUE 8) — in a module that
   configures the overlapped transport (a ``overlap_reduce=`` step
   builder, or the CLI's ``overlap_key(args)`` derivation), every
   ``ladder_step_key(...)`` call must pass the ``overlap=`` coordinate:
   a key without it serves a step traced for the wrong schedule /
   bucket layout after a ladder transition — the same bug class with a
   transport coordinate.
5. **block-blind ladder keys** (ISSUE 9/12) — same shape for the
   block-scaled wire: a module that configures block scaling (a
   ``block_scale=`` step builder, or ``block_key(args)``) must pass
   the ``block=`` coordinate through every ``ladder_step_key(...)``
   call — the blocked wire is a DIFFERENT accumulation numerics and
   wire layout (ring sidecar, ZeRO-2 all_to_all, the blocked scan),
   so a ladder transition must never serve a step traced for the
   other block coordinate.
"""

from __future__ import annotations

from typing import Iterator

from ..core import Finding, register
from ..project import ProjectGraph, ProjectRule


@register
class Retrace(ProjectRule):
    id = "retrace"
    summary = ("jit built per-iteration, or step tables keyed outside "
               "ladder_step_key/StepTable — the PR 5 stale-step bug "
               "class")

    def check(self, project: ProjectGraph) -> Iterator[Finding]:
        by_mod: dict = {}
        for fkey, f, mod in project.iter_functions():
            by_mod.setdefault(fkey[0], (mod, []))[1].append(f)
            for site in f["jit_in_loop"]:
                yield Finding(
                    path=mod["path"], line=site["line"], col=site["col"],
                    rule=self.id,
                    message=(
                        "jax.jit constructed inside a loop with no "
                        "`key not in cache` memoization guard — every "
                        "iteration builds a fresh jitted callable and "
                        "re-traces; hoist it, or route variants through "
                        "transport.StepTable / utils.cache.LRUCache"))
            yield from self._half_keyed(f, mod)
            yield from self._fstr_keys(f, mod)
        for mod, funcs in by_mod.values():
            yield from self._overlap_blind(mod, funcs)
            yield from self._block_blind(mod, funcs)

    def _half_keyed(self, f, mod) -> Iterator[Finding]:
        sups = f["supervisor_objs"]
        kinds = set(sups.values())
        if not {"transport", "precision"} <= kinds:
            return
        for sub in f["table_subscripts"]:
            if sub["key_kind"] != "attr":
                continue
            if sups.get(sub["key_obj"]) is None:
                continue
            if sub["key_attr"] not in ("mode", "fmt"):
                continue
            other = ("PrecisionSupervisor"
                     if sups[sub["key_obj"]] == "transport"
                     else "TransportSupervisor")
            yield Finding(
                path=mod["path"], line=sub["line"], col=sub["col"],
                rule=self.id,
                message=(
                    f"step table keyed by bare "
                    f"{sub['key_obj']}.{sub['key_attr']} while a "
                    f"{other} is live in the same scope — the key "
                    f"misses that supervisor's coordinate, so the table "
                    f"serves a step traced for the WRONG "
                    f"{'format' if other == 'PrecisionSupervisor' else 'transport'} "
                    f"after a transition (the PR 5 ladder_step_key "
                    f"bug); derive keys with "
                    f"precision.ladder_step_key(transport, precision)"))

    def _overlap_blind(self, mod, funcs) -> Iterator[Finding]:
        """Module-scope check 4: overlap-configured modules must thread
        the overlap coordinate through every ladder key.  The trigger is
        deliberately module-wide — the CLIs derive ``ov_key`` in main()
        and subscript the table from the same scope, but a step builder
        configured in a helper still poisons every key site in the
        file."""
        configures_overlap = any(
            "overlap_reduce" in call["kw"]
            or call["callee"].split(".")[-1] == "overlap_key"
            for f in funcs for call in f["calls"])
        if not configures_overlap:
            return
        for f in funcs:
            for call in f["calls"]:
                if call["callee"].split(".")[-1] != "ladder_step_key":
                    continue
                if "overlap" in call["kw"] or call["star"]:
                    continue
                yield Finding(
                    path=mod["path"], line=call["line"], col=call["col"],
                    rule=self.id,
                    message=(
                        "ladder_step_key(...) without the overlap= "
                        "coordinate in a module that configures the "
                        "overlapped transport — after a ladder "
                        "transition the table would serve a step traced "
                        "for the wrong schedule / bucket layout; pass "
                        "overlap=utils.config.overlap_key(args) (None "
                        "when the run has no overlap surface)"))

    def _block_blind(self, mod, funcs) -> Iterator[Finding]:
        """Module-scope check 5: block-scale-configured modules must
        thread the block coordinate through every ladder key (the
        ``_overlap_blind`` shape for the ISSUE 9/12 blocked wires —
        module-wide trigger for the same reason)."""
        configures_block = any(
            "block_scale" in call["kw"]
            or call["callee"].split(".")[-1] == "block_key"
            for f in funcs for call in f["calls"])
        if not configures_block:
            return
        for f in funcs:
            for call in f["calls"]:
                if call["callee"].split(".")[-1] != "ladder_step_key":
                    continue
                if "block" in call["kw"] or call["star"]:
                    continue
                yield Finding(
                    path=mod["path"], line=call["line"], col=call["col"],
                    rule=self.id,
                    message=(
                        "ladder_step_key(...) without the block= "
                        "coordinate in a module that configures the "
                        "block-scaled wire — after a ladder transition "
                        "the table would serve a step traced for the "
                        "wrong block layout/numerics; pass "
                        "block=utils.config.block_key(args) (None when "
                        "the run has no block surface)"))

    def _fstr_keys(self, f, mod) -> Iterator[Finding]:
        jit_tables = {t["name"] for t in f["jit_tables"] if t["jit"]}
        for sub in f["table_subscripts"]:
            if sub["key_kind"] != "fstr":
                continue
            if sub["table"] not in jit_tables:
                continue
            yield Finding(
                path=mod["path"], line=sub["line"], col=sub["col"],
                rule=self.id,
                message=(
                    f"jitted-step table {sub['table']!r} keyed by an "
                    f"f-string — stringified cache keys conflate "
                    f"distinct configs and churn under formatting "
                    f"drift; use structured tuple keys via "
                    f"transport.StepTable (ladder_step_key) or "
                    f"utils.cache.LRUCache"))
