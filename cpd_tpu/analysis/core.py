"""Lint engine: rule registry, suppression handling, file walking, output.

Stdlib-only by contract (see package docstring).  A rule is a class with
an ``id``, a one-line ``summary``, and a ``check(ctx)`` generator that
yields ``Finding``s; it registers itself with the ``@register``
decorator.  The engine parses each file once, hands every rule the same
``ModuleContext`` (AST + source + small shared analyses), then filters
findings through the suppression comments.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Callable, Iterable, Iterator, Optional

__all__ = ["Finding", "ModuleContext", "Rule", "register", "all_rules",
           "module_rules", "project_rules", "program_rules", "host_rules",
           "lint_source", "lint_file", "lint_tree", "lint_parsed",
           "run_project_rules", "run_program_rules_on",
           "render_text", "render_json"]


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One lint hit, anchored to a source location (1-based line)."""
    path: str
    line: int
    col: int
    rule: str
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


_DISABLE_LINE = re.compile(r"#\s*cpd:\s*disable=([A-Za-z0-9_,\- ]+)")
_DISABLE_FILE = re.compile(r"#\s*cpd:\s*disable-file=([A-Za-z0-9_,\- ]+)")
_SKIP_FILE = re.compile(r"#\s*cpd:\s*skip-file\b")


def _parse_rule_list(blob: str) -> set[str]:
    """Rule ids from a directive's payload: comma-separated, with
    anything after whitespace inside a segment treated as justification
    text (`disable=format-bounds -- fast path intended` names one
    rule)."""
    out: set[str] = set()
    for segment in blob.split(","):
        tokens = segment.split()
        if tokens:
            out.add(tokens[0])
    return out


class Suppressions:
    """Per-file view of ``# cpd:`` directives.

    Directives are read from actual COMMENT tokens (via ``tokenize``),
    never from string literals — a docstring that *documents* the
    suppression syntax must not silently disable the linter for its
    file.  If tokenization fails the file gets no suppressions (the
    conservative direction: findings stay visible)."""

    def __init__(self, src: str):
        self.skip_file = False
        self.file_rules: set[str] = set()
        self.line_rules: dict[int, set[str]] = {}
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(src).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return
        for tok in tokens:
            if tok.type != tokenize.COMMENT or "cpd:" not in tok.string:
                continue
            line = tok.start[0]
            if _SKIP_FILE.search(tok.string):
                self.skip_file = True
            m = _DISABLE_FILE.search(tok.string)
            if m:
                self.file_rules |= _parse_rule_list(m.group(1))
            m = _DISABLE_LINE.search(tok.string)
            if m:
                self.line_rules.setdefault(line, set()).update(
                    _parse_rule_list(m.group(1)))

    def allows(self, f: Finding, stmt_line: Optional[int] = None) -> bool:
        """True when the finding survives suppression.  ``stmt_line`` is
        the first line of the enclosing statement — a directive there
        also covers findings anchored to argument nodes on later lines
        of a multi-line call."""
        if "all" in self.file_rules or f.rule in self.file_rules:
            return False
        for line in {f.line, stmt_line or f.line}:
            at_line = self.line_rules.get(line, ())
            if "all" in at_line or f.rule in at_line:
                return False
        return True

    def to_dict(self) -> dict:
        """JSON form (cached alongside the module summary so warm runs
        filter project-rule findings without re-tokenizing)."""
        return {"skip": self.skip_file,
                "file_rules": sorted(self.file_rules),
                "line_rules": {str(k): sorted(v)
                               for k, v in self.line_rules.items()}}

    @classmethod
    def from_dict(cls, data: dict) -> "Suppressions":
        inst = cls.__new__(cls)
        inst.skip_file = bool(data.get("skip", False))
        inst.file_rules = set(data.get("file_rules", ()))
        inst.line_rules = {int(k): set(v)
                           for k, v in data.get("line_rules", {}).items()}
        return inst


class ModuleContext:
    """Everything a rule needs about one parsed file, computed once."""

    def __init__(self, path: str, src: str, tree: ast.Module):
        self.path = path
        self.src = src
        self.tree = tree
        self.lines = src.splitlines()
        # top-level NAME = <int> bindings, for resolving tile-size
        # constants like _LANES = 128 in shape literals
        self.int_constants: dict[str, int] = {}
        for node in tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                val = literal_int(node.value)
                if val is not None:
                    self.int_constants[node.targets[0].id] = val

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(path=self.path, line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0), rule=rule,
                       message=message)


class Rule:
    """Base class; subclasses set ``id``/``summary`` and implement
    ``check``.  ``scope`` is "module" (check(ctx) per parsed file),
    "host" (check(ctx) per parsed file too, but running the per-class
    host-runtime dataflow of analysis/host/ — thread-safety, bounded
    growth, resource lifecycle, one-clock), "project" (check(project)
    once per run, over the whole-program graph — see
    analysis/project.py's ProjectRule), or "program" (check(programs)
    over the traced-jaxpr facts of the registered compiled programs —
    analysis/ir/, run only under ``--ir``).  Module and host rules ride
    the same per-file fingerprint cache entry."""

    id: str = ""
    summary: str = ""
    scope: str = "module"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError


_REGISTRY: dict[str, Rule] = {}


def register(cls: type) -> type:
    """Class decorator adding a rule instance to the global registry."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> dict[str, Rule]:
    return dict(_REGISTRY)


def module_rules() -> dict[str, Rule]:
    return {k: r for k, r in _REGISTRY.items() if r.scope == "module"}


def project_rules() -> dict[str, Rule]:
    return {k: r for k, r in _REGISTRY.items() if r.scope == "project"}


def program_rules() -> dict[str, Rule]:
    return {k: r for k, r in _REGISTRY.items() if r.scope == "program"}


def host_rules() -> dict[str, Rule]:
    return {k: r for k, r in _REGISTRY.items() if r.scope == "host"}


# ---------------------------------------------------------------------------
# shared AST helpers (used by the rule modules)
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> str:
    """'jax.experimental.pallas.BlockSpec' for nested Attribute/Name
    chains; '' when the expression is not a plain dotted name."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def base_name(node: ast.AST) -> str:
    """Last segment of a dotted callee ('psum' for lax.psum)."""
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else ""


def literal_int(node: ast.AST) -> Optional[int]:
    """Int value of a literal (including unary minus); None otherwise.
    bools are NOT ints here (True is not a valid exp_bits)."""
    if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)):
        inner = literal_int(node.operand)
        return None if inner is None else -inner
    if (isinstance(node, ast.Constant) and type(node.value) is int):
        return node.value
    return None


def literal_float(node: ast.AST) -> Optional[float]:
    """Float value of a numeric literal (int or float, +/-)."""
    if (isinstance(node, ast.UnaryOp)
            and isinstance(node.op, (ast.USub, ast.UAdd))):
        inner = literal_float(node.operand)
        if inner is None:
            return None
        return -inner if isinstance(node.op, ast.USub) else inner
    if (isinstance(node, ast.Constant)
            and type(node.value) in (int, float)):
        return float(node.value)
    return None


def string_literals(node: ast.AST) -> Iterator[ast.Constant]:
    """Every string-constant node inside ``node`` (inclusive)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub


def call_arg(call: ast.Call, pos: Optional[int],
             kw: Optional[str]) -> Optional[ast.AST]:
    """Argument at positional index ``pos`` or keyword ``kw`` (keyword
    wins); None when absent or hidden behind *args/**kwargs."""
    if kw is not None:
        for k in call.keywords:
            if k.arg == kw:
                return k.value
    if pos is not None and pos < len(call.args):
        arg = call.args[pos]
        if not isinstance(arg, ast.Starred):
            return arg
    return None


def iter_functions(tree: ast.Module) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def walk_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Like ast.walk but does not descend into nested function/class
    scopes (the nested def/lambda node itself IS yielded).  Scope-local
    dataflow rules use this so a statement is analyzed in exactly one
    scope."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                yield child
                continue
            stack.append(child)


def unwrap_partial(node: ast.AST) -> Optional[ast.Call]:
    """For ``functools.partial(f, ...)`` / ``partial(f, ...)`` return the
    partial Call; None otherwise."""
    if (isinstance(node, ast.Call)
            and base_name(node.func) == "partial"):
        return node
    return None


def jit_decoration(fn: ast.FunctionDef) -> Optional[ast.Call]:
    """If ``fn`` is decorated with jax.jit (bare, called, or via
    functools.partial), return a Call carrying the jit kwargs (synthetic
    empty Call for the bare form); else None."""
    for dec in fn.decorator_list:
        name = dotted_name(dec)
        if name in ("jax.jit", "jit"):
            return ast.Call(func=dec, args=[], keywords=[])
        if isinstance(dec, ast.Call):
            if dotted_name(dec.func) in ("jax.jit", "jit"):
                return dec
            part = unwrap_partial(dec)
            if (part is not None and part.args
                    and dotted_name(part.args[0]) in ("jax.jit", "jit")):
                return ast.Call(func=part.args[0], args=[],
                                keywords=part.keywords)
    return None


def int_tuple_literal(node: ast.AST,
                      consts: dict[str, int]) -> Optional[list[Optional[int]]]:
    """Resolve a tuple/list literal of dimension sizes; each element is an
    int (literal or module-level constant) or None when unresolvable.
    Returns None when ``node`` is not a tuple/list literal at all."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out: list[Optional[int]] = []
    for el in node.elts:
        v = literal_int(el)
        if v is None and isinstance(el, ast.Name):
            v = consts.get(el.id)
        out.append(v)
    return out


# ---------------------------------------------------------------------------
# running
# ---------------------------------------------------------------------------

class LintError(Exception):
    """Internal failure (unreadable file, rule crash) — exit code 2."""


def _stmt_start_map(tree: ast.Module) -> dict[int, int]:
    """line -> first line of the innermost statement covering it, so a
    suppression on a multi-line call's first line covers findings
    anchored to argument nodes on its later lines (nested statements
    start later, so max() picks the innermost)."""
    stmt_start: dict[int, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.stmt) and node.end_lineno is not None:
            for line in range(node.lineno, node.end_lineno + 1):
                stmt_start[line] = max(stmt_start.get(line, 1),
                                       node.lineno)
    return stmt_start


def lint_parsed(path: str, src: str, tree: ast.Module,
                select: Optional[Iterable[str]] = None
                ) -> tuple[list[Finding], dict]:
    """Module- and host-rule pass over one parsed file.

    Returns ``(suppression-filtered findings, module summary)`` — the
    summary (analysis/project.py) carries the whole-program facts PLUS
    the file's suppression/statement tables under ``"_lint"``, so the
    fingerprint cache can serve project rules without re-parsing."""
    from .project import summarize_module
    supp = Suppressions(src)
    stmt_start = _stmt_start_map(tree)
    try:
        summary = summarize_module(path, src, tree)
    except LintError:
        raise
    except Exception as e:
        # an extraction crash is an engine bug and must surface as
        # exit 2 (analyzer broke), never as exit 1 (lint findings) —
        # CI distinguishes them (docs/ANALYSIS.md contract)
        raise LintError(
            f"{path}: summary extraction crashed: "
            f"{type(e).__name__}: {e}") from e
    summary["_lint"] = {"supp": supp.to_dict(),
                        "stmt_start": {str(k): v
                                       for k, v in stmt_start.items()}}
    if supp.skip_file:
        return [], summary
    ctx = ModuleContext(path, src, tree)
    wanted = set(select) if select is not None else None
    out: list[Finding] = []
    for rule_id, rule in sorted(_REGISTRY.items()):
        if rule.scope not in ("module", "host"):
            continue
        if wanted is not None and rule_id not in wanted:
            continue
        try:
            for f in rule.check(ctx):
                if supp.allows(f, stmt_start.get(f.line)):
                    out.append(f)
        except LintError:
            raise
        except Exception as e:  # a rule crash is an engine bug: code 2
            raise LintError(
                f"{path}: rule {rule_id!r} crashed: {type(e).__name__}: "
                f"{e}") from e
    return sorted(out), summary


def run_project_rules(summaries: list[dict],
                      select: Optional[Iterable[str]] = None
                      ) -> list[Finding]:
    """Whole-program pass: build one ProjectGraph over `summaries`, run
    every selected project-scoped rule, and filter each finding through
    its file's cached suppression tables."""
    from .project import ProjectGraph
    wanted = set(select) if select is not None else None
    active = [(rid, r) for rid, r in sorted(_REGISTRY.items())
              if r.scope == "project"
              and (wanted is None or rid in wanted)]
    if not active:
        return []
    graph = ProjectGraph(summaries)
    supp_by_path: dict[str, tuple] = {}
    for s in summaries:
        meta = s.get("_lint", {})
        supp = Suppressions.from_dict(meta.get("supp", {}))
        stmt_start = {int(k): v
                      for k, v in meta.get("stmt_start", {}).items()}
        supp_by_path[s["path"]] = (supp, stmt_start)
    out: list[Finding] = []
    for rule_id, rule in active:
        try:
            for f in rule.check(graph):
                supp, stmt_start = supp_by_path.get(f.path, (None, {}))
                if supp is None:
                    out.append(f)
                elif not supp.skip_file and supp.allows(
                        f, stmt_start.get(f.line)):
                    out.append(f)
        except LintError:
            raise
        except Exception as e:
            raise LintError(
                f"project rule {rule_id!r} crashed: "
                f"{type(e).__name__}: {e}") from e
    return out


def run_program_rules_on(progset,
                         select: Optional[Iterable[str]] = None
                         ) -> list[Finding]:
    """Program-scope pass over one traced ProgramSet (analysis/ir/run.py
    builds it).  Comment suppressions do not apply — findings anchor at
    declaration sites whose files the IR pass never parses; config
    exemptions still do (applied by the caller, engine.py)."""
    wanted = set(select) if select is not None else None
    out: list[Finding] = []
    for rule_id, rule in sorted(_REGISTRY.items()):
        if rule.scope != "program":
            continue
        if wanted is not None and rule_id not in wanted:
            continue
        try:
            out.extend(rule.check(progset))
        except LintError:
            raise
        except Exception as e:
            raise LintError(
                f"program rule {rule_id!r} crashed: "
                f"{type(e).__name__}: {e}") from e
    return out


def _apply_config(findings: list[Finding], config) -> list[Finding]:
    if config is None:
        return findings
    return [f for f in findings if not config.exempts(f.rule, f.path)]


def lint_source(src: str, path: str = "<string>",
                select: Optional[Iterable[str]] = None,
                config=None) -> list[Finding]:
    """Lint one source blob (module rules + a single-file project pass);
    returns suppression- and config-filtered findings.  With no explicit
    `config` the built-in defaults apply (analysis/config.py)."""
    from .config import DEFAULT_CONFIG
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        raise LintError(f"{path}: syntax error at line {e.lineno}: "
                        f"{e.msg}") from e
    findings, summary = lint_parsed(path, src, tree, select=select)
    findings = findings + run_project_rules([summary], select=select)
    return sorted(_apply_config(findings,
                                config if config is not None
                                else DEFAULT_CONFIG))


def lint_file(path: str,
              select: Optional[Iterable[str]] = None,
              config=None) -> list[Finding]:
    try:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
    except OSError as e:
        raise LintError(f"cannot read {path}: {e}") from e
    return lint_source(src, path=path, select=select, config=config)


# Directories never worth descending into.  ``fixtures`` holds test DATA
# (including the analysis rules' deliberately-bad snippets); the lint
# tests exercise those files explicitly via lint_file.
_SKIP_DIRS = {"__pycache__", ".git", ".hypothesis", ".pytest_cache",
              "node_modules", "fixtures", ".jax_cache"}


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for root in paths:
        if os.path.isfile(root):
            yield root
            continue
        if not os.path.isdir(root):
            # a vanished root must fail loudly (exit 2), not shrink the
            # gate's coverage to whatever paths still exist
            raise LintError(f"path does not exist: {root}")
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_tree(paths: Iterable[str],
              select: Optional[Iterable[str]] = None,
              on_file: Optional[Callable[[str], None]] = None,
              config=None) -> list[Finding]:
    """Lint every .py under ``paths`` (files or directories): one
    module-rule pass per file plus ONE whole-program pass over all of
    them.  With no explicit `config` the pyproject.toml discovered above
    the first path wins, then the built-in defaults (analysis/config.py
    precedence).  For the cached engine see analysis/engine.py."""
    from .config import load_config
    if config is None:
        config = load_config(paths)
    findings: list[Finding] = []
    summaries: list[dict] = []
    for path in iter_python_files(paths):
        if on_file is not None:
            on_file(path)
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
        except OSError as e:
            raise LintError(f"cannot read {path}: {e}") from e
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            raise LintError(f"{path}: syntax error at line {e.lineno}: "
                            f"{e.msg}") from e
        local, summary = lint_parsed(path, src, tree, select=select)
        findings.extend(local)
        summaries.append(summary)
    findings.extend(run_project_rules(summaries, select=select))
    return sorted(_apply_config(findings, config))


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def render_text(findings: list[Finding]) -> str:
    lines = [f"{f.path}:{f.line}:{f.col + 1}: {f.rule}: {f.message}"
             for f in findings]
    n = len(findings)
    lines.append(f"{n} finding{'s' if n != 1 else ''}")
    return "\n".join(lines)


def render_json(findings: list[Finding], files_checked: int,
                files_parsed: Optional[int] = None,
                programs_checked: Optional[int] = None,
                programs_traced: Optional[int] = None) -> str:
    by_rule: dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    payload = {
        "version": 1,
        "files_checked": files_checked,
        "findings": [f.to_dict() for f in findings],
        "counts": by_rule,
    }
    if files_parsed is not None:
        # additive cache telemetry (v1-compatible): how many files the
        # run actually re-parsed vs served from the fingerprint cache
        payload["files_parsed"] = files_parsed
    if programs_checked is not None:
        # additive --ir telemetry: registered programs checked, and how
        # many actually re-traced (0 on a warm unchanged tree)
        payload["programs_checked"] = programs_checked
        payload["programs_traced"] = programs_traced
    return json.dumps(payload, indent=2, sort_keys=True)
