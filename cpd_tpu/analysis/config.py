"""Lint configuration: per-rule path exemptions + root paths.

Three precedence layers, highest wins **per top-level key** (``exempt``,
``paths``) — a higher layer that defines a key replaces the lower
layer's value for that key wholesale, it does not merge into it
(documented in docs/ANALYSIS.md, pinned by tests/test_analysis.py):

    1. an explicit ``--config FILE`` on the CLI
    2. the ``[tool.cpd-lint]`` table of the pyproject.toml discovered by
       walking up from the first linted path
    3. the built-in defaults below

The built-in defaults exist so bare ``lint_source`` calls (unit tests,
editor integrations with no project file) behave like the shipped
pyproject: the ``swallow`` rule's resilience/ carve-out and
``compat-drift``'s compat.py carve-out live in CONFIG, not in rule code.

TOML support is a deliberate stdlib-only subset (``tomllib`` only
appeared in Python 3.11 and this package must run on 3.10): sections,
string/int/float/bool scalars, and (possibly multi-line) arrays of
strings.  Quoted keys (``"compat-drift" = [...]``) are supported — rule
ids contain hyphens.  That covers every [tool.cpd-lint] shape we
document; anything fancier (inline tables, dotted keys in assignments)
raises ``ConfigError`` rather than being silently misread.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Iterable, Optional

__all__ = ["Config", "ConfigError", "DEFAULT_CONFIG", "load_config",
           "parse_toml_subset", "discover_pyproject"]


class ConfigError(Exception):
    """Unreadable/unsupported config input — maps to exit code 2."""


# rule id -> path fragments (matched as substrings of the /-normalized
# finding path).  These defaults mirror the shipped pyproject.toml.
_DEFAULT_EXEMPT = {
    "swallow": ("cpd_tpu/resilience/",),
    "compat-drift": ("cpd_tpu/compat.py",),
    # the reference-parity stdout line protocol (TableLogger /
    # ProgressPrinter / format_validation_line) that draw_curve.py
    # greps — legacy by design, exempt from the obs-print discipline
    "obs-print": ("cpd_tpu/utils/logging.py",),
    # obs/timing.py IS the one clock — the only file allowed to read
    # time.perf_counter/time.time directly (host scope, ISSUE 16)
    "host-clock": ("cpd_tpu/obs/timing.py",),
    # the analyzer is a batch CLI process: its graphs/caches are
    # bounded by the size of the linted tree and freed at exit — not
    # step/request-clock growth on a long-lived host object
    "host-unbounded": ("cpd_tpu/analysis/",),
}


@dataclasses.dataclass(frozen=True)
class Config:
    """Resolved lint configuration (see module docstring)."""
    exempt: dict = dataclasses.field(
        default_factory=lambda: dict(_DEFAULT_EXEMPT))
    paths: tuple = ()              # default roots when CLI gives none
    source: str = "builtin"        # where the winning table came from

    def exempts(self, rule: str, path: str) -> bool:
        """True when `rule` findings in `path` are configured away."""
        fragments = self.exempt.get(rule)
        if not fragments:
            return False
        norm = os.path.normpath(path).replace(os.sep, "/")
        return any(frag in norm for frag in fragments)

    def fingerprint(self) -> str:
        """Stable digest of the RESOLVED config (exemptions + paths),
        folded into the lint caches' fingerprints (analysis/cache.py)
        so editing pyproject's [tool.cpd-lint] invalidates warm runs —
        a cache entry is only as fresh as the policy it was filtered
        and keyed under."""
        import hashlib
        import json
        blob = json.dumps(
            {"exempt": {k: sorted(v) for k, v in self.exempt.items()},
             "paths": list(self.paths)}, sort_keys=True)
        return hashlib.sha1(blob.encode()).hexdigest()[:16]


DEFAULT_CONFIG = Config()


# ---------------------------------------------------------------------------
# the TOML subset
# ---------------------------------------------------------------------------

_SECTION = re.compile(r'^\[([^\]]+)\]\s*(?:#.*)?$')
_KEY = re.compile(r'^\s*(?:"([^"]+)"|\'([^\']+)\'|([A-Za-z0-9_-]+))\s*=\s*(.*)$')


def _strip_comment(line: str) -> str:
    """Drop a # comment that is not inside a string literal."""
    out, in_str, quote = [], False, ""
    for ch in line:
        if in_str:
            out.append(ch)
            if ch == quote:
                in_str = False
        elif ch in "\"'":
            in_str, quote = True, ch
            out.append(ch)
        elif ch == "#":
            break
        else:
            out.append(ch)
    return "".join(out).rstrip()


class _Unsupported:
    """Sentinel for TOML values outside the supported subset.  They are
    tolerated everywhere EXCEPT inside [tool.cpd-lint] itself — a
    pyproject full of inline tables must still load, but a cpd-lint key
    we cannot read must fail loudly (validated in _config_from_table)."""
    def __repr__(self):
        return "<unsupported toml value>"


_UNSUPPORTED = _Unsupported()


def _parse_scalar(text: str):
    text = text.strip()
    if len(text) >= 2 and text[0] in "\"'" and text[-1] == text[0]:
        return text[1:-1]
    if text in ("true", "false"):
        return text == "true"
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        return _UNSUPPORTED


def _parse_array(text: str):
    body = text.strip()[1:-1]
    items, cur, in_str, quote = [], [], False, ""
    for ch in body:
        if in_str:
            cur.append(ch)
            if ch == quote:
                in_str = False
        elif ch in "\"'":
            in_str, quote = True, ch
            cur.append(ch)
        elif ch == ",":
            if "".join(cur).strip():
                items.append(_parse_scalar("".join(cur)))
            cur = []
        else:
            cur.append(ch)
    if "".join(cur).strip():
        items.append(_parse_scalar("".join(cur)))
    return items


def parse_toml_subset(text: str) -> dict:
    """Parse the documented TOML subset into nested dicts (module
    docstring).  Sections create nesting; unsupported syntax raises
    ConfigError instead of misparsing."""
    root: dict = {}
    current = root
    section: list = []
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = _strip_comment(lines[i])
        i += 1
        if not line.strip():
            continue
        m = _SECTION.match(line.strip())
        if m:
            current = root
            section = [p.strip().strip('"\'')
                       for p in m.group(1).split(".")]
            for part in section:
                current = current.setdefault(part, {})
                if not isinstance(current, dict):
                    raise ConfigError(
                        f"section [{m.group(1)}] collides with a value")
            continue
        m = _KEY.match(line)
        if not m:
            # outside [tool.cpd-lint]: tolerate the rest of TOML (a
            # pyproject full of dotted keys must still load).  INSIDE
            # our table, a line we cannot read is a loud error — a
            # silently-dropped exemption would un-gate the tree.
            if section[:2] == ["tool", "cpd-lint"]:
                raise ConfigError(
                    f"unsupported TOML syntax inside [tool.cpd-lint]: "
                    f"{line.strip()!r} (the supported subset is plain "
                    f"`key = value` / quoted keys / string arrays — "
                    f"see analysis/config.py)")
            continue
        key = m.group(1) or m.group(2) or m.group(3)
        value = m.group(4).strip()
        if value.startswith("["):
            # arrays may span lines: accumulate until brackets balance
            while value.count("[") > value.count("]"):
                if i >= len(lines):
                    raise ConfigError(f"unterminated array for key {key!r}")
                value += " " + _strip_comment(lines[i]).strip()
                i += 1
            current[key] = _parse_array(value)
        else:
            current[key] = _parse_scalar(value)
    return root


# ---------------------------------------------------------------------------
# loading + precedence
# ---------------------------------------------------------------------------

def discover_pyproject(paths: Iterable[str]) -> Optional[str]:
    """Walk up from each path in turn (or the CWD when none are given)
    until some pyproject.toml is found."""
    paths = list(paths) or [os.getcwd()]
    for root in paths:
        probe = os.path.abspath(root)
        if os.path.isfile(probe):
            probe = os.path.dirname(probe)
        while True:
            cand = os.path.join(probe, "pyproject.toml")
            if os.path.isfile(cand):
                return cand
            parent = os.path.dirname(probe)
            if parent == probe:
                break              # this root exhausted; try the next
            probe = parent
    return None


def _table_from_file(path: str) -> Optional[dict]:
    """The [tool.cpd-lint] table of `path` (or the file's top level when
    it IS a standalone cpd-lint config with no [tool] nesting)."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = parse_toml_subset(fh.read())
    except OSError as e:
        raise ConfigError(f"cannot read config {path}: {e}") from e
    table = data.get("tool", {}).get("cpd-lint")
    if table is None and os.path.basename(path) != "pyproject.toml":
        # standalone config file: top-level keys are the table
        table = {k: v for k, v in data.items()
                 if k in ("exempt", "paths")}
    return table if table else None


def _config_from_table(table: dict, source: str,
                       base: Config) -> Config:
    exempt = base.exempt
    paths = base.paths
    raw_exempt = table.get("exempt")
    if raw_exempt is not None:
        if not isinstance(raw_exempt, dict):
            raise ConfigError("[tool.cpd-lint.exempt] must be a table of "
                              "rule-id -> path-fragment arrays")
        exempt = {}
        for rule, frags in raw_exempt.items():
            if isinstance(frags, str):
                frags = [frags]
            if not isinstance(frags, list) or not all(
                    isinstance(f, str) for f in frags):
                raise ConfigError(f"exempt.{rule!s} must be a "
                                  f"path-fragment string array (got an "
                                  f"unsupported TOML value — see the "
                                  f"supported subset in "
                                  f"analysis/config.py)")
            exempt[rule] = tuple(frags)
    raw_paths = table.get("paths")
    if raw_paths is not None:
        if not isinstance(raw_paths, list) or not all(
                isinstance(p, str) for p in raw_paths):
            raise ConfigError("[tool.cpd-lint].paths must be a string array")
        paths = tuple(raw_paths)
    return Config(exempt=exempt, paths=paths, source=source)


def load_config(paths: Iterable[str] = (),
                cli_path: Optional[str] = None) -> Config:
    """Resolve the active Config through the precedence chain
    (module docstring): --config file > discovered pyproject > builtin,
    applied PER KEY — a --config that sets only ``paths`` still takes
    its ``exempt`` table from the discovered pyproject."""
    cfg = DEFAULT_CONFIG
    pyproject = discover_pyproject(paths)
    if pyproject is not None:
        table = _table_from_file(pyproject)
        if table:
            cfg = _config_from_table(table, pyproject, cfg)
    if cli_path is None:
        return cfg
    if not os.path.isfile(cli_path):
        raise ConfigError(f"config file does not exist: {cli_path}")
    table = _table_from_file(cli_path)
    if table:
        cfg = _config_from_table(table, cli_path, cfg)
    return cfg
