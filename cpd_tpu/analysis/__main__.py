"""CLI entry: ``python -m cpd_tpu.analysis <paths> [--format=...]``.

Exit-code contract (stable for tooling; pinned by tests/test_analysis.py
and [project.scripts] cpd-lint):

    0  clean — every checked file passed every selected rule
    1  findings — at least one unsuppressed finding was reported
    2  internal error — bad arguments, unreadable/ unparsable input, or
       a rule crash (details on stderr)
"""

from __future__ import annotations

import argparse
import sys

from .core import (LintError, all_rules, lint_tree, render_json,
                   render_text)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cpd_tpu.analysis",
        description="JAX/precision-aware static lint for the cpd_tpu "
                    "tree (stdlib-only; see docs/ANALYSIS.md)")
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format (default: text)")
    p.add_argument("--select", default=None, metavar="RULE[,RULE...]",
                   help="run only these rule ids")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit 0")
    return p


def main(argv=None) -> int:
    try:
        args = _build_parser().parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on bad usage and 0 on --help; map both into
        # the documented contract (0 stays 0, anything else is 2)
        return 0 if e.code in (0, None) else 2

    rules = all_rules()
    if args.list_rules:
        for rule_id, rule in sorted(rules.items()):
            print(f"{rule_id:16s} {rule.summary}")
        return 0

    if not args.paths:
        print("error: no paths given (try --help)", file=sys.stderr)
        return 2

    select = None
    if args.select is not None:
        select = {r.strip() for r in args.select.split(",") if r.strip()}
        unknown = select - rules.keys()
        if unknown:
            print(f"error: unknown rule id(s): {sorted(unknown)}; "
                  f"known: {sorted(rules)}", file=sys.stderr)
            return 2

    files = []
    try:
        findings = lint_tree(args.paths, select=select,
                             on_file=files.append)
    except LintError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if not files:
        print(f"error: no Python files under {args.paths}",
              file=sys.stderr)
        return 2

    if args.format == "json":
        print(render_json(findings, files_checked=len(files)))
    else:
        print(render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
