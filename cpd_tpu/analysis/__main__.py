"""CLI entry: ``python -m cpd_tpu.analysis <paths> [options]``.

Exit-code contract (stable for tooling; pinned by tests/test_analysis.py
and [project.scripts] cpd-lint — CI depends on the 1-vs-2 distinction to
tell "findings" from "the analyzer itself broke"):

    0  clean — every checked file passed every selected rule
    1  findings — at least one unsuppressed finding was reported
    2  internal error — bad arguments, unreadable/ unparsable input, a
       rule crash, a broken git environment under --changed-only, or an
       unusable --config (details on stderr)

Options beyond PR 1's:

    --format=sarif       SARIF 2.1.0 (CI PR annotation; analysis/sarif.py)
    --no-cache           bypass the .cpd-lint-cache/ fingerprint cache
    --cache-dir DIR      cache location (default ./.cpd-lint-cache)
    --changed-only       lint only git-changed .py files (working tree +
                         index; --since REF diffs against a ref instead)
    --config FILE        explicit [tool.cpd-lint] config (precedence:
                         this > discovered pyproject > built-in)
    --explain RULE       print a rule's catalog entry + the minimal
                         bad/good example from its fixtures, then exit 0

v3 (ISSUE 14) — the program-contract scope:

    --ir                 ALSO run the jaxpr-level program rules
                         (analysis/ir/): trace the registered compiled
                         programs abstractly on CPU and check their
                         collective-schedule / wire-ledger / bitwise-
                         stability / overlap / retrace contracts.  The
                         only mode that imports jax.  With --ir and no
                         paths, ONLY the program pass runs (the CI
                         ``ir-contracts`` gate).  A program that fails
                         to trace is a finding AND exit 2 — an
                         unverifiable contract means the gate is down,
                         not clean.
"""

from __future__ import annotations

import argparse
import os
import sys

from .core import (LintError, all_rules, program_rules, render_json,
                   render_text)
from .config import ConfigError
from .engine import DEFAULT_CACHE_DIR, run_analysis
from .sarif import render_sarif


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cpd_tpu.analysis",
        description="JAX/precision-aware static lint for the cpd_tpu "
                    "tree — per-file rules + a whole-program pass "
                    "(stdlib-only; see docs/ANALYSIS.md)")
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text", help="output format (default: text)")
    p.add_argument("--select", default=None, metavar="RULE[,RULE...]",
                   help="run only these rule ids")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit 0")
    p.add_argument("--explain", default=None, metavar="RULE",
                   help="print a rule's catalog entry + fixture example")
    p.add_argument("--config", default=None, metavar="FILE",
                   help="explicit cpd-lint config (overrides pyproject)")
    p.add_argument("--no-cache", action="store_true",
                   help="bypass the per-file fingerprint cache")
    p.add_argument("--cache-dir", default=DEFAULT_CACHE_DIR,
                   metavar="DIR", help="fingerprint cache directory")
    p.add_argument("--changed-only", action="store_true",
                   help="lint only git-changed files under the paths")
    p.add_argument("--since", default=None, metavar="REF",
                   help="with --changed-only: diff against REF instead "
                        "of the working tree (CI passes the PR base)")
    p.add_argument("--ir", action="store_true",
                   help="also run the jaxpr-level program-contract "
                        "rules (imports jax; see docs/ANALYSIS.md v3)")
    return p


def _fixtures_dir() -> str:
    """tests/fixtures/analysis relative to the repo checkout (the
    package's grandparent); '' when not running from a checkout."""
    pkg = os.path.dirname(os.path.abspath(__file__))        # analysis/
    repo = os.path.dirname(os.path.dirname(pkg))            # repo root
    d = os.path.join(repo, "tests", "fixtures", "analysis")
    return d if os.path.isdir(d) else ""


def _explain(rule_id: str) -> int:
    rules = all_rules()
    rule = rules.get(rule_id)
    if rule is None:
        print(f"error: unknown rule id {rule_id!r}; known: "
              f"{sorted(rules)}", file=sys.stderr)
        return 2
    print(f"{rule.id} [{rule.scope}]")
    print(f"  {rule.summary}\n")
    # rules living one-per-module document themselves in the module
    # docstring; modules holding several (analysis/ir/rules.py) put the
    # catalog entry on the CLASS — prefer the specific one
    import inspect
    doc = inspect.cleandoc(
        type(rule).__doc__
        or sys.modules[type(rule).__module__].__doc__ or "")
    if doc:
        print(doc + "\n")
    fdir = _fixtures_dir()
    if not fdir:
        print("(fixture examples unavailable outside a repo checkout)")
        return 0
    stem = rule_id.replace("-", "_")
    for kind, label in (("bad", "FIRES on (minimal bad example)"),
                        ("good", "stays SILENT on (clean twin)")):
        path = os.path.join(fdir, f"{stem}_{kind}.py")
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8") as fh:
            body = fh.read().rstrip()
        print(f"--- {label}: tests/fixtures/analysis/{stem}_{kind}.py ---")
        print(body)
        print()
    return 0


def main(argv=None) -> int:
    try:
        args = _build_parser().parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on bad usage and 0 on --help; map both into
        # the documented contract (0 stays 0, anything else is 2)
        return 0 if e.code in (0, None) else 2

    rules = all_rules()
    if args.list_rules:
        for rule_id, rule in sorted(rules.items()):
            print(f"{rule_id:20s} [{rule.scope:7s}] {rule.summary}")
        return 0
    if args.explain is not None:
        return _explain(args.explain)

    if not args.paths and not args.ir:
        # [tool.cpd-lint].paths provides the default roots; bare
        # invocation with neither is an error, not an empty pass.
        # (--ir with no paths is the program-pass-only gate.)
        try:
            from .config import load_config
            cfg = load_config([], cli_path=args.config)
        except ConfigError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        args.paths = list(cfg.paths)
    if not args.paths and not args.ir:
        print("error: no paths given and no [tool.cpd-lint].paths "
              "configured (try --help)", file=sys.stderr)
        return 2

    select = None
    if args.select is not None:
        select = {r.strip() for r in args.select.split(",") if r.strip()}
        unknown = select - rules.keys()
        if unknown:
            print(f"error: unknown rule id(s): {sorted(unknown)}; "
                  f"known: {sorted(rules)}", file=sys.stderr)
            return 2

    try:
        result = run_analysis(
            args.paths, select=select, config_path=args.config,
            use_cache=not args.no_cache, cache_dir=args.cache_dir,
            changed_only=args.changed_only, since=args.since,
            ir=args.ir)
    except (LintError, ConfigError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if result.files_checked == 0:
        if args.changed_only:
            # an empty diff is a legitimate clean PR, not an error —
            # but with --ir the program pass ran regardless, and its
            # findings/trace-failures must reach the output and the
            # exit code below, never be discarded by the empty diff
            if not args.ir:
                print("no changed Python files under the given paths",
                      file=sys.stderr)
                return 0
            print("no changed Python files under the given paths; "
                  "program-contract results follow", file=sys.stderr)
        elif args.paths or not args.ir:
            # explicit paths with nothing under them stay a loud
            # error even under --ir (the file gate checked NOTHING);
            # only the deliberate no-paths `--ir` program-pass-only
            # mode is exempt
            print(f"error: no Python files under {args.paths}",
                  file=sys.stderr)
            return 2

    findings = result.findings
    if args.format == "json":
        print(render_json(findings, files_checked=result.files_checked,
                          files_parsed=result.files_parsed,
                          programs_checked=(result.programs_checked
                                            if args.ir else None),
                          programs_traced=(result.programs_traced
                                           if args.ir else None)))
    elif args.format == "sarif":
        print(render_sarif(findings, base_dir=os.getcwd()))
    else:
        print(render_text(findings))
    if result.trace_failures and (
            select is None or select & set(program_rules())):
        # every program rule's verdict covers only the programs that
        # TRACED — so any selection touching the program scope is
        # unverified when a registered program failed to trace, not
        # just an explicit ir-trace selection.  The exit code must say
        # "the analyzer could not verify", never "clean"/"findings".
        print(f"error: {result.trace_failures} registered program(s) "
              f"failed to trace — program contracts unverified",
              file=sys.stderr)
        return 2
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
