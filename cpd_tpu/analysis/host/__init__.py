"""cpd_tpu.analysis.host — the host-runtime contract scope (v4).

The fourth rule scope, beside module/project/program: a lightweight
per-class dataflow over the repo's long-lived host-side runtime objects
(engines, routers, supervisors, recorders, schedulers) checking the
four contract families hand review kept re-finding across the
serve/fleet/obs/resilience arcs (ISSUE 16):

  host-race       attributes touched both from a thread/Timer callback
                  and from main-loop methods with inconsistent locking,
                  and unsynchronized container mutation across threads
  host-unbounded  module-lifetime containers grown on the step/request
                  clock with no cap, eviction or prune on any path
                  (the ResultStore/fleet-control-plane defect class)
  host-leak       acquire/start without a with/finally-scoped or
                  class-managed release (open(), profiler windows,
                  Timer/Thread lifecycles, bare lock acquires)
  host-clock      wall-clock reads outside obs/timing.py — every timer
                  rides obs.timing.now()/Stopwatch (durations) or
                  obs.timing.epoch() (timestamps), the one-clock
                  doctrine

Host rules carry ``scope = "host"`` and run per *file* inside
``core.lint_parsed`` right beside the module scope — the dataflow is
per-class, so no cross-file graph is needed and every verdict rides
the existing fingerprint cache, suppression grammar, ``[tool.cpd-lint]``
exemptions, SARIF output and ``--explain`` machinery unchanged
(SCHEMA_VERSION folds the scope into the cache fingerprint).

Stdlib-only like every other AST scope: ``ast`` in, findings out, no
jax anywhere — the canary-jax-current job runs this pass too.
"""

from . import rules  # noqa: F401  (registration side effect)

__all__ = ["rules"]
