"""The four host-runtime contract rules (docs/ANALYSIS.md, "v4 — host
contracts").  Each class docstring is its ``--explain`` catalog entry;
fixture pairs live at tests/fixtures/analysis/host_*_{bad,good}.py.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from ..core import (Finding, ModuleContext, Rule, dotted_name, register,
                    walk_scope)
from .facts import Access, ClassFacts, _WRITE_KINDS, facts_for

__all__ = ["HostRaceRule", "HostUnboundedRule", "HostLeakRule",
           "HostClockRule"]


def _lockset_matches(a: Access, facts: ClassFacts) -> Set[str]:
    """Concrete lock attrs an access holds; ``*_locked`` methods hold
    every lock the class owns."""
    if "*" in a.locks:
        return set(facts.lock_attrs) or {"*"}
    return set(a.locks)


@register
class HostRaceRule(Rule):
    """Attributes shared between a thread/Timer callback and main-loop
    methods must use one lock discipline.

    Host objects that spawn workers — ``threading.Thread(target=
    self.m)``, ``threading.Timer(t, self.m)`` — share ``self`` between
    the worker and every main-loop method.  For each attribute touched
    on *both* sides (``__init__`` excluded: it runs before the thread
    exists) with at least one write, the rule checks the lock
    discipline:

    * **inconsistent locking** — some access holds a lock (``with
      self._lock:`` block, or a ``*_locked``-suffixed helper, the
      repo's held-lock naming convention) but the two sides share no
      common lock: flagged.  This is the watchdog ``_context`` defect
      shape — armed under the lock, read lock-free in the timer
      callback.
    * **no locking anywhere** — only *structure mutation* of a
      container crosses the thread boundary unlocked (append/pop/del/
      element store from one side while the other side touches the same
      container): flagged.  Plain attribute rebinds of flags
      (``self.tripped = True``) are CPython-atomic and deliberately NOT
      flagged.

    Deliberately NOT flagged: attrs that are themselves synchronized
    objects — ``queue.Queue`` and friends, ``threading.Event``, the
    locks themselves (utils/prefetch.py's queue+event handshake is the
    sanctioned pattern); accesses in ``__init__``; classes that spawn
    no workers.

    Fix: take the same lock on both sides (snapshot under the lock,
    then work on the snapshot — resilience/watchdog.py ``_fire``), move
    the data onto a queue, or suppress with a written justification.
    """

    id = "host-race"
    summary = ("thread/Timer-shared attribute accessed without a common "
               "lock across the thread boundary")
    scope = "host"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for facts in facts_for(ctx):
            if not facts.thread_entries:
                continue
            attrs = {a.attr for a in facts.accesses
                     if a.attr not in facts.safe_attrs
                     and a.attr not in facts.methods}
            for attr in sorted(attrs):
                acc = [a for a in facts.attr_accesses(attr)
                       if a.method != "__init__"]
                thread_side = [a for a in acc
                               if a.method in facts.thread_entries]
                main_side = [a for a in acc
                             if a.method not in facts.thread_entries]
                if not thread_side or not main_side:
                    continue
                if not any(a.kind in _WRITE_KINDS for a in acc):
                    continue
                locksets = [_lockset_matches(a, facts) for a in acc]
                if set.intersection(*locksets):
                    continue  # common lock covers every access
                if any(locksets):
                    bare = next((a for a in thread_side
                                 if not _lockset_matches(a, facts)),
                                None) or next(
                        a for a in acc if not _lockset_matches(a, facts))
                    yield ctx.finding(
                        self.id, bare.node,
                        f"{facts.name}.{attr} uses inconsistent locking: "
                        f"accessed lock-free in {bare.method}() but under "
                        f"a lock elsewhere, and thread entry "
                        f"{sorted(facts.thread_entries)} shares it with "
                        f"the main loop — hold the same lock on every "
                        f"side (snapshot under the lock, then use the "
                        f"snapshot)")
                    continue
                mutation = next(
                    (a for a in acc if a.kind in ("grow", "shrink",
                                                  "mutate")), None)
                if mutation is not None:
                    yield ctx.finding(
                        self.id, mutation.node,
                        f"{facts.name}.{attr} container structure is "
                        f"mutated across the thread boundary with no "
                        f"lock at all ({mutation.method}() vs the other "
                        f"side) — guard with a threading.Lock or hand "
                        f"the data over a queue.Queue")


@register
class HostUnboundedRule(Rule):
    """Module-lifetime containers grown on the step/request clock need a
    cap, eviction, or prune path.

    The generalized ResultStore defect (PR 10) and the fleet
    control-plane logs (PR 13): an attribute initialized in
    ``__init__`` as an unbounded container (list/dict/set literal or
    ctor, ``deque()`` *without* ``maxlen=``) and grown inside non-init
    methods (``append``/``add``/``extend``/``setdefault``/``update``,
    dict element store, ``+=``) is flagged when the class has **no
    shrink path anywhere**: on a long-lived host object every step or
    request leaks a little memory forever.

    Recognized shrink paths (any one silences the attr class-wide):
    ``pop``/``popleft``/``popitem``/``remove``/``discard``/``clear``
    calls, ``del self.X[...]``, and a rebind whose RHS is an empty
    literal or *reads the attr itself* — the comprehension-filter prune
    (``self.placement = {k: v for k, v in self.placement.items() if
    ...}``) and slice-truncate (``self.log = self.log[-k:]``) idioms.
    A ``load_state_dict``-style rebind from foreign data is NOT a
    shrink — restoring a snapshot does not bound future growth.

    Deliberately NOT flagged: ``deque(maxlen=...)`` (bounded by
    construction); growth only inside ``__init__``; nested structures
    (``self.logs[i].append(...)`` mutates an element, not the tracked
    attr — flag the element's own class if it is long-lived).

    Fix: bound it (``deque(maxlen=)``, explicit cap + eviction like
    serve/engine.py's ResultStore, periodic prune), or suppress with a
    justification stating the actual bound.
    """

    id = "host-unbounded"
    summary = ("module-lifetime container grown on the step/request "
               "clock with no cap or prune path")
    scope = "host"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for facts in facts_for(ctx):
            for attr, (kind, _anchor) in sorted(facts.containers.items()):
                acc = facts.attr_accesses(attr)
                grows = [a for a in acc
                         if a.kind == "grow" and a.method != "__init__"]
                if not grows:
                    continue
                if any(a.kind == "shrink" for a in acc):
                    continue
                first = min(grows, key=lambda a: getattr(
                    a.node, "lineno", 1))
                yield ctx.finding(
                    self.id, first.node,
                    f"{facts.name}.{attr} ({kind}, initialized in "
                    f"__init__) grows in {first.method}() and the class "
                    f"has no shrink path — bound it (deque(maxlen=), "
                    f"cap+eviction, periodic prune) or suppress with "
                    f"the actual bound")


def _finally_bodies(fn: ast.AST) -> List[ast.AST]:
    out: List[ast.AST] = []
    for node in walk_scope(fn):
        if isinstance(node, ast.Try):
            out.extend(node.finalbody)
    return out


def _name_used(nodes: List[ast.AST], name: str,
               method: Optional[str] = None) -> bool:
    """Is ``name.method(...)`` (or any use of ``name``, when method is
    None) present under ``nodes``?"""
    for root in nodes:
        for sub in ast.walk(root):
            if method is None:
                if isinstance(sub, ast.Name) and sub.id == name:
                    return True
            elif (isinstance(sub, ast.Call)
                  and isinstance(sub.func, ast.Attribute)
                  and sub.func.attr == method
                  and isinstance(sub.func.value, ast.Name)
                  and sub.func.value.id == name):
                return True
    return False


def _escapes(fn: ast.AST, name: str) -> bool:
    """Conservative ownership-transfer check: the local is returned,
    yielded, stored on self/another object, or passed to a call —
    someone else may close it."""
    for node in walk_scope(fn):
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            if node.value is not None and _name_used([node.value], name):
                return True
        elif isinstance(node, ast.Call):
            args = list(node.args) + [kw.value for kw in node.keywords]
            if any(isinstance(a, ast.Name) and a.id == name for a in args):
                return True
        elif isinstance(node, ast.Assign):
            if (_name_used([node.value], name)
                    and any(not isinstance(t, ast.Name)
                            for t in node.targets)):
                return True
    return False


@register
class HostLeakRule(Rule):
    """Acquire/start without a with/finally-scoped or class-managed
    release: file handles, profiler windows, Timer/Thread lifecycles,
    bare lock acquires.

    The PR 11 defect family (five profiler-close-in-finally fixes),
    made mechanical.  Four shapes:

    * ``f = open(...)`` into a **local**: must be ``with``-scoped or
      ``f.close()``-d inside a ``finally:`` — a close on the straight
      path still leaks on exceptions.  Ownership transfer (the handle
      is returned, stored on ``self``/another object, or passed to a
      call) silences the local check.
    * ``self._fh = open(...)``: the class must contain
      ``self._fh.close()`` somewhere (utils/logging.py's ScalarWriter
      close/__exit__ pattern).
    * ``start_trace`` without ``stop_trace`` anywhere in the same
      class — an unclosed profiler window.
    * ``threading.Timer``/``Thread`` stored on ``self`` and
      ``.start()``-ed: Timers need a ``.cancel()`` path, Threads need
      ``.join()`` or ``daemon=True`` (the watchdog cancel/daemon
      discipline).  ``.acquire()`` on an attr with no ``.release()``
      class-wide is flagged the same way (``with lock:`` never trips
      this).

    Deliberately NOT flagged: ``with open(...) as f`` and expression
    opens (``open(p).read()`` — idiomatic for short reads, CPython
    refcounting closes promptly); classes pairing start/stop
    (utils/profiling.py's StepProfiler); daemon workers.

    Fix: use ``with``; move the release into ``finally``; add the
    ``close``/``cancel``/``join`` lifecycle method and call it from
    ``close()``/``__exit__``.
    """

    id = "host-leak"
    summary = ("resource acquired/started without a with/finally-scoped "
               "or class-managed release")
    scope = "host"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        yield from self._check_classes(ctx)
        yield from self._check_functions(ctx)

    def _check_classes(self, ctx: ModuleContext) -> Iterator[Finding]:
        for facts in facts_for(ctx):
            for attr, anchor in sorted(facts.open_attrs.items()):
                if "close" not in facts.calls_on(attr):
                    yield ctx.finding(
                        self.id, anchor,
                        f"{facts.name}.{attr} = open(...) but the class "
                        f"never calls self.{attr}.close() — add a "
                        f"close()/__exit__ lifecycle method")
            if facts.start_trace_sites and facts.stop_trace_count == 0:
                yield ctx.finding(
                    self.id, facts.start_trace_sites[0],
                    f"{facts.name} opens a profiler window (start_trace) "
                    f"but never calls stop_trace — close the window in "
                    f"finally or a close() method")
            for attr, (kind, anchor, daemon) in sorted(
                    facts.worker_attrs.items()):
                calls = facts.calls_on(attr)
                if "start" not in calls:
                    continue
                if kind == "Timer" and "cancel" not in calls:
                    yield ctx.finding(
                        self.id, anchor,
                        f"{facts.name}.{attr} is a started threading."
                        f"Timer with no cancel() path — cancel it in "
                        f"close()/stop() or the timer outlives the "
                        f"object")
                elif kind == "Thread" and not daemon and "join" not in calls:
                    yield ctx.finding(
                        self.id, anchor,
                        f"{facts.name}.{attr} is a started non-daemon "
                        f"Thread with no join() path — join it in "
                        f"close() or mark it daemon")
            for attr in sorted({a.attr for a in facts.accesses
                                if a.call == "acquire"}):
                if "release" not in facts.calls_on(attr):
                    acq = next(a for a in facts.accesses
                               if a.attr == attr and a.call == "acquire")
                    yield ctx.finding(
                        self.id, acq.node,
                        f"{facts.name}.{attr}.acquire() with no "
                        f"release() class-wide — use `with self.{attr}:` "
                        f"or release in finally")

    def _check_functions(self, ctx: ModuleContext) -> Iterator[Finding]:
        """Function-local opens (free functions AND methods): open()
        without with/finally-close."""
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in walk_scope(fn):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)
                        and dotted_name(node.value.func) == "open"):
                    continue
                name = node.targets[0].id
                closed_in_finally = _name_used(
                    _finally_bodies(fn), name, "close")
                if closed_in_finally or _escapes(fn, name):
                    continue
                yield ctx.finding(
                    self.id, node,
                    f"{name} = open(...) in {fn.name}() is closed on no "
                    f"finally path — use `with open(...) as {name}:` or "
                    f"close in finally (leaks the handle on exceptions)")


_CLOCK_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "time.process_time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.now", "datetime.utcnow",
    "datetime.today",
}
_TIME_FUNCS = {"time", "time_ns", "monotonic", "monotonic_ns",
               "perf_counter", "perf_counter_ns", "process_time",
               "process_time_ns"}


@register
class HostClockRule(Rule):
    """Wall-clock reads belong in obs/timing.py — everything else rides
    the one shared clock.

    The one-clock doctrine (docs/OBSERVABILITY.md): durations come from
    ``obs.timing.now()``/``Stopwatch`` (monotonic ``perf_counter``
    under the hood) and epoch timestamps from ``obs.timing.epoch()``
    — so tests can virtualize time, traces from different subsystems
    line up, and nobody diffs ``time.time()`` against ``perf_counter``.
    Flags any call of ``time.time``/``monotonic``/``perf_counter``/
    ``process_time`` (and ``_ns`` variants, including names imported
    via ``from time import ...``) or ``datetime.now``/``utcnow``/
    ``today`` outside the exempted ``cpd_tpu/obs/timing.py``.

    Deliberately NOT flagged: ``time.sleep`` (a delay, not a clock
    read); ``date.today`` on a bare ``date``; clock names inside string
    literals (subprocess scripts in tests).

    Fix: ``from cpd_tpu.obs.timing import now, epoch, Stopwatch`` —
    ``now()`` for durations, ``epoch()`` for the sanctioned wall-clock
    timestamp, or route through an existing Stopwatch.
    """

    id = "host-clock"
    summary = ("wall-clock read outside obs/timing.py — use "
               "obs.timing.now()/epoch()/Stopwatch")
    scope = "host"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        from_time: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for alias in node.names:
                    if alias.name in _TIME_FUNCS:
                        from_time.add(alias.asname or alias.name)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            hit = (name in _CLOCK_CALLS
                   or (isinstance(node.func, ast.Name)
                       and node.func.id in from_time))
            if hit:
                yield ctx.finding(
                    self.id, node,
                    f"wall-clock read {name or node.func.id}() outside "
                    f"obs/timing.py — use obs.timing.now() for "
                    f"durations, obs.timing.epoch() for timestamps "
                    f"(one-clock doctrine, docs/ANALYSIS.md v4)")
