"""Per-class fact extraction for the host scope.

One walk per file, memoized on the ModuleContext, shared by all four
host rules.  For every ``class`` in the module we record:

  * the method table and which methods are *thread entries* — targets
    of ``threading.Thread(target=self.m)`` / ``threading.Timer(t,
    self.m)`` plus their transitive ``self.m2()`` call closure;
  * the lock inventory (attrs assigned ``threading.Lock/RLock/
    Condition/Semaphore``) and the thread-safe allowlist (``queue.*``
    queues, ``threading.Event`` — objects whose own methods
    synchronize, so cross-thread use is fine without a lock);
  * every ``self.X`` access with its method, access *kind* and the
    *lockset* held at the access site (``with self._lock:`` blocks;
    methods named ``*_locked`` are treated as holding every lock — the
    repo's convention for lock-held helpers);
  * container lifecycle: attrs initialized as unbounded containers in
    ``__init__`` (list/dict/set literals or ctors, ``deque()`` without
    ``maxlen=``), where they grow, and whether any shrink path exists
    (``pop/popleft/popitem/remove/discard/clear``, ``del self.X[..]``,
    or a rebind that resets to an empty literal / filters-truncates a
    read of ``self.X`` itself — the comprehension-prune and
    slice-truncate idioms);
  * resource lifecycle: ``self.X = open(...)`` / ``threading.Timer`` /
    ``threading.Thread`` attrs, whether they are started, and whether
    the class provides the matching ``close/cancel/join`` (or marks
    the thread daemon); ``start_trace``/``stop_trace`` and
    ``acquire``/``release`` call tallies.

Access kinds:  ``read`` — plain load or non-mutating method call;
``write`` — attribute rebind; ``grow``/``shrink`` — container size
change; ``mutate`` — in-place structure mutation that is neither
(element store on a list, ``sort``, attribute-set on the referenced
object).  The race rule treats everything but ``read`` as a write.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..core import ModuleContext, dotted_name

__all__ = ["Access", "ClassFacts", "facts_for"]

# -- vocabulary ---------------------------------------------------------------

_LOCK_TYPES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
# Objects whose methods synchronize internally: sharing them across
# threads without an explicit lock is the *intended* use (Prefetcher's
# queue.Queue + threading.Event handshake).
_SAFE_TYPES = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "Event"}

_GROW_CALLS = {"append", "appendleft", "add", "extend", "insert",
               "setdefault", "update"}
_SHRINK_CALLS = {"pop", "popleft", "popitem", "remove", "discard", "clear"}
# In-place mutations that are neither grow nor shrink for sure, but do
# change structure — relevant to the race rule's cross-thread check.
_MUTATE_CALLS = {"put", "put_nowait", "get", "get_nowait", "move_to_end",
                 "sort", "reverse"}

_WRITE_KINDS = {"write", "grow", "shrink", "mutate"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"``; anything else -> None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_empty_container(node: ast.AST) -> bool:
    """Empty literal or zero-arg container ctor — a reset-to-empty RHS."""
    if isinstance(node, (ast.List, ast.Set)) and not node.elts:
        return True
    if isinstance(node, ast.Dict) and not node.keys:
        return True
    if isinstance(node, ast.Call) and not node.args and not node.keywords:
        base = dotted_name(node.func).rsplit(".", 1)[-1]
        return base in ("list", "dict", "set", "deque", "OrderedDict")
    return False


def _references_self_attr(node: ast.AST, attr: str) -> bool:
    """Does the expression read ``self.<attr>`` anywhere?  A rebind whose
    RHS re-reads the attr (``self.xs = [x for x in self.xs if ...]``,
    ``self.xs = self.xs[-k:]``) is a prune, not fresh growth."""
    for sub in ast.walk(node):
        if _self_attr(sub) == attr:
            return True
    return False


def _container_kind(value: ast.AST) -> Optional[Tuple[str, bool]]:
    """Classify an ``__init__`` RHS as ``(kind, bounded)`` if it builds a
    container; None otherwise.  Only ``deque(maxlen=...)`` is bounded by
    construction."""
    if isinstance(value, ast.List):
        return ("list", False)
    if isinstance(value, ast.Dict):
        return ("dict", False)
    if isinstance(value, ast.Set):
        return ("set", False)
    if isinstance(value, ast.Call):
        base = dotted_name(value.func).rsplit(".", 1)[-1]
        if base == "deque":
            bounded = (any(kw.arg == "maxlen" for kw in value.keywords)
                       or len(value.args) >= 2)
            return ("deque", bounded)
        if base in ("list", "set"):
            return (base, False)
        if base in ("dict", "OrderedDict", "defaultdict", "Counter"):
            return ("dict", False)
    return None


# -- data ---------------------------------------------------------------------

@dataclass
class Access:
    """One ``self.X`` touch: where, what kind, and under which locks."""

    attr: str
    method: str
    kind: str                 # read | write | grow | shrink | mutate
    locks: frozenset          # lock attrs held; "*" = all (``*_locked``)
    node: ast.AST             # anchor for findings
    call: Optional[str] = None  # method name for self.X.m() accesses


@dataclass
class ClassFacts:
    """Everything the host rules need to know about one class."""

    name: str
    node: ast.ClassDef
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    lock_attrs: Set[str] = field(default_factory=set)
    safe_attrs: Set[str] = field(default_factory=set)
    thread_entries: Set[str] = field(default_factory=set)
    # attr -> (kind, init anchor) for unbounded-at-init containers
    containers: Dict[str, Tuple[str, ast.AST]] = field(default_factory=dict)
    accesses: List[Access] = field(default_factory=list)
    # attr -> ("Timer"|"Thread", ctor anchor, daemon flag)
    worker_attrs: Dict[str, Tuple[str, ast.AST, bool]] = field(
        default_factory=dict)
    open_attrs: Dict[str, ast.AST] = field(default_factory=dict)
    start_trace_sites: List[ast.AST] = field(default_factory=list)
    stop_trace_count: int = 0
    # self.m() call edges: caller method -> callee method names
    call_edges: Dict[str, Set[str]] = field(default_factory=dict)

    def attr_accesses(self, attr: str) -> List[Access]:
        return [a for a in self.accesses if a.attr == attr]

    def calls_on(self, attr: str) -> Set[str]:
        """All ``self.<attr>.m()`` method names seen class-wide."""
        return {a.call for a in self.accesses
                if a.attr == attr and a.call is not None}


# -- extraction ---------------------------------------------------------------

_WORKER_TYPES = {"Timer": "Timer", "Thread": "Thread"}


def _worker_ctor(value: ast.AST, threading_names: Set[str]) -> Optional[str]:
    """Is this RHS a ``threading.Timer(...)`` / ``threading.Thread(...)``
    construction?  Bare ``Timer(...)`` only counts when the name was
    imported from threading — the repo has unrelated Timer classes
    (obs stopwatches, DavidNet parity)."""
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func)
    if name in ("threading.Timer", "threading.Thread"):
        return name.rsplit(".", 1)[-1]
    if name in _WORKER_TYPES and name in threading_names:
        return name
    return None


def _thread_target(call: ast.Call) -> Optional[str]:
    """Method name of a ``self.m`` passed as a Thread target / Timer
    function (kwarg or the Timer's second positional)."""
    for kw in call.keywords:
        if kw.arg in ("target", "function"):
            return _self_attr(kw.value)
    name = dotted_name(call.func).rsplit(".", 1)[-1]
    if name == "Timer" and len(call.args) >= 2:
        return _self_attr(call.args[1])
    return None


def _daemon_true(call: ast.Call) -> bool:
    return any(kw.arg == "daemon"
               and isinstance(kw.value, ast.Constant) and kw.value.value is True
               for kw in call.keywords)


class _MethodScanner:
    """Walks one method body tracking the set of held locks, recording
    every ``self.X`` access.  Does not descend into nested defs/lambdas/
    classes (their execution time is unknowable statically)."""

    def __init__(self, facts: ClassFacts, method: str,
                 threading_names: Set[str]):
        self.facts = facts
        self.method = method
        self.threading_names = threading_names
        self.base_locks: frozenset = (
            frozenset(["*"]) if method.endswith("_locked") else frozenset())

    def add(self, attr: str, node: ast.AST, kind: str,
            locks: frozenset, call: Optional[str] = None) -> None:
        self.facts.accesses.append(Access(
            attr=attr, method=self.method, kind=kind, locks=locks,
            node=node, call=call))

    def scan(self, node: ast.AST, locks: frozenset) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.With):
            held = set(locks)
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and attr in self.facts.lock_attrs:
                    held.add(attr)
                else:
                    self.scan(item.context_expr, locks)
            for stmt in node.body:
                self.scan(stmt, frozenset(held))
            return
        if isinstance(node, ast.Assign):
            for target in node.targets:
                self._store(target, node.value, locks)
            self.scan(node.value, locks)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._store(node.target, node.value, locks)
                self.scan(node.value, locks)
            return
        if isinstance(node, ast.AugAssign):
            attr = _self_attr(node.target)
            if attr is not None:
                kind = "grow" if attr in self.facts.containers else "write"
                self.add(attr, node, kind, locks)
            else:
                self.scan(node.target, locks)
            self.scan(node.value, locks)
            return
        if isinstance(node, ast.Delete):
            for target in node.targets:
                attr = None
                if isinstance(target, ast.Subscript):
                    attr = _self_attr(target.value)
                if attr is None:
                    attr = _self_attr(target)
                if attr is not None:
                    self.add(attr, target, "shrink", locks)
                else:
                    self.scan(target, locks)
            return
        if isinstance(node, ast.Call):
            self._call(node, locks)
            return
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None:
                self.add(attr, node, "read", locks)
                return
        for child in ast.iter_child_nodes(node):
            self.scan(child, locks)

    def _store(self, target: ast.AST, value: ast.AST,
               locks: frozenset) -> None:
        attr = _self_attr(target)
        if attr is not None:
            kind = "write"
            if self.method != "__init__" and attr in self.facts.containers:
                if (_is_empty_container(value)
                        or _references_self_attr(value, attr)):
                    kind = "shrink"
            worker = _worker_ctor(value, self.threading_names)
            if worker is not None and attr not in self.facts.worker_attrs:
                self.facts.worker_attrs[attr] = (
                    worker, target, _daemon_true(value))
            if (isinstance(value, ast.Call)
                    and dotted_name(value.func) == "open"
                    and attr not in self.facts.open_attrs):
                self.facts.open_attrs[attr] = target
            self.add(attr, target, kind, locks)
            return
        if isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)
            if attr is not None:
                # a[key] = v grows dicts/sets; on lists it replaces an
                # element (no growth) — still a structure mutation.
                kind_info = self.facts.containers.get(attr)
                kind = ("grow" if kind_info and kind_info[0] == "dict"
                        else "mutate")
                if isinstance(target.slice, ast.Slice):
                    kind = "mutate"  # slice-assign rewrites in place
                self.add(attr, target, kind, locks)
                return
            self.scan(target.value, locks)
            self.scan(target.slice, locks)
            return
        if isinstance(target, ast.Attribute):
            # self.X.y = v — attribute-set on the referenced object
            attr = _self_attr(target.value)
            if attr is not None:
                self.add(attr, target, "mutate", locks, call=None)
                if (target.attr == "daemon"
                        and attr in self.facts.worker_attrs):
                    kind, anchor, _ = self.facts.worker_attrs[attr]
                    self.facts.worker_attrs[attr] = (kind, anchor, True)
                return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._store(elt, value, locks)
            return
        self.scan(target, locks)

    def _call(self, node: ast.Call, locks: frozenset) -> None:
        func = node.func
        handled = False
        if isinstance(func, ast.Attribute):
            owner = _self_attr(func.value)
            if owner is not None:
                # self.X.m(...)
                m = func.attr
                if m in _GROW_CALLS:
                    kind = "grow"
                elif m in _SHRINK_CALLS:
                    kind = "shrink"
                elif m in _MUTATE_CALLS:
                    kind = "mutate"
                else:
                    kind = "read"
                self.add(owner, node, kind, locks, call=m)
                handled = True
            else:
                callee = _self_attr(func)
                if callee is not None:
                    # self.m(...) — call edge (or callable-attr read)
                    self.facts.call_edges.setdefault(
                        self.method, set()).add(callee)
                    self.add(callee, node, "read", locks, call=None)
                    handled = True
        name = dotted_name(func)
        if name.endswith("start_trace"):
            self.facts.start_trace_sites.append(node)
        elif name.endswith("stop_trace"):
            self.facts.stop_trace_count += 1
        target = _thread_target(node) if _worker_ctor(
            node, self.threading_names) else None
        if target is not None:
            self.facts.thread_entries.add(target)
        if not handled:
            self.scan(func, locks)
        for arg in node.args:
            self.scan(arg, locks)
        for kw in node.keywords:
            self.scan(kw.value, locks)


def _scan_init_layout(facts: ClassFacts, threading_names: Set[str]) -> None:
    """First pass over ``__init__`` (and class-level assigns): lock
    inventory, thread-safe allowlist, container initializers."""
    def classify(target: ast.AST, value: ast.AST) -> None:
        attr = _self_attr(target)
        if attr is None and isinstance(target, ast.Name):
            attr = target.id  # class-level ``spans: deque = deque()``
        if attr is None or value is None:
            return
        if isinstance(value, ast.Call):
            base = dotted_name(value.func).rsplit(".", 1)[-1]
            if base in _LOCK_TYPES:
                facts.lock_attrs.add(attr)
                facts.safe_attrs.add(attr)
                return
            if base in _SAFE_TYPES:
                facts.safe_attrs.add(attr)
                return
        kind = _container_kind(value)
        if kind is not None and not kind[1]:
            facts.containers.setdefault(attr, (kind[0], target))

    for stmt in facts.node.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                classify(t, stmt.value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            classify(stmt.target, stmt.value)
    init = facts.methods.get("__init__")
    if init is not None:
        for stmt in ast.walk(init):
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    classify(t, stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                classify(stmt.target, stmt.value)


def _close_thread_entries(facts: ClassFacts) -> None:
    """Transitive closure of thread entries over self.m() call edges."""
    work = list(facts.thread_entries)
    while work:
        m = work.pop()
        for callee in facts.call_edges.get(m, ()):
            if callee in facts.methods and callee not in facts.thread_entries:
                facts.thread_entries.add(callee)
                work.append(callee)


def _threading_names(tree: ast.Module) -> Set[str]:
    """Names bound by ``from threading import ...`` at module level."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "threading":
            for alias in node.names:
                names.add(alias.asname or alias.name)
    return names


def _extract(tree: ast.Module) -> List[ClassFacts]:
    threading_names = _threading_names(tree)
    out: List[ClassFacts] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        facts = ClassFacts(name=node.name, node=node)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                facts.methods[stmt.name] = stmt
        _scan_init_layout(facts, threading_names)
        for name, method in facts.methods.items():
            scanner = _MethodScanner(facts, name, threading_names)
            for stmt in method.body:
                scanner.scan(stmt, scanner.base_locks)
        _close_thread_entries(facts)
        out.append(facts)
    return out


def facts_for(ctx: ModuleContext) -> List[ClassFacts]:
    """Extract (memoized per ModuleContext — all host rules share one
    walk per file)."""
    cached = getattr(ctx, "_host_facts", None)
    if cached is None:
        cached = _extract(ctx.tree)
        setattr(ctx, "_host_facts", cached)
    return cached
