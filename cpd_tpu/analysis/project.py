"""Whole-program analysis layer: module summaries, import resolution,
call graph, and a small constant/format lattice — stdlib-``ast`` only.

PR 1's linter is per-file; every cross-module incident since slipped
exactly through the file boundary (the man<2 ladder rung that would die
inside ``pack_exmy`` mid-jit, the ``ladder_step_key`` re-trace bug fixed
in PR 5 review).  This layer gives rules the missing whole-program view:

* **ModuleSummary** — a JSON-serializable fact extraction from one
  parsed file: imports, per-scope function summaries (calls with
  abstract argument values, collective axis literals, ppermute
  permutation analyses, Kahan unpacks, wire-payload name closures,
  jit-construction sites, step-table subscripts), module-level
  constants, declared mesh axes, suppression tables.  Because rules
  consume summaries — never raw ASTs — the fingerprint cache
  (analysis/cache.py) can serve a warm run with ZERO re-parses.

* **ProjectGraph** — summaries indexed and linked: dotted-import
  resolution across the analyzed tree (absolute + relative, one level
  of ``__init__`` re-export chasing), a call graph that also follows
  bare-name function references (step functions passed to
  ``shard_map``/``jax.jit`` are edges too), and an interprocedural
  constant lattice.

The lattice is deliberately small: abstract values are sets of concrete
constants (strings, ints, floats, tuples — which covers eXmY ``(exp,
man)`` pairs, ladder rung lists, axis names and wire-word widths) plus a
``("packed", (exp, man))`` marker for ``pack_exmy`` results (and the
``("packed", (exp, man), block)`` marker for ``pack_exmy_blocked``'s
sidecar wire).  Joins are
set unions; a set wider than ``_WIDEN_CAP`` widens to TOP (``None``).
Parameter environments are propagated caller→callee over the call graph
to a bounded fixpoint (``_PROPAGATE_ROUNDS``), so a format literal
constructed in a trainer CLI is visible at the ``pack_exmy`` sink four
calls away.  Everything undecidable stays TOP and rules only fire on
KNOWN-bad values — the analysis is unsound-but-precise by design: it
exists to catch the silently-wrong-number bug class, not to prove the
tree correct.

Project-scoped rules subclass ``ProjectRule`` (``scope = "project"``)
and implement ``check(project)``; the engine builds one graph per run
(a single-module graph for ``lint_source``/``lint_file``) and filters
their findings through the same per-file suppression tables as module
rules.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, Optional

from .core import (Finding, Rule, base_name, call_arg, dotted_name,
                   literal_int)

__all__ = ["ProjectGraph", "ProjectRule", "summarize_module",
           "module_name_for", "TOP"]

TOP = None                # lattice top: "any value"
_WIDEN_CAP = 8            # value-set size that widens to TOP
_PROPAGATE_ROUNDS = 6     # caller->callee binding fixpoint bound
_AVAL_DEPTH = 3           # nested abstract-value extraction depth

# collective -> axis-argument position/keyword (the axis-name rule's
# vocabulary, restated here so extraction never imports the rule module)
COLLECTIVES = {
    "psum": (1, "axis_name"), "pmean": (1, "axis_name"),
    "pmax": (1, "axis_name"), "pmin": (1, "axis_name"),
    "ppermute": (1, "axis_name"), "pshuffle": (1, "axis_name"),
    "psum_scatter": (1, "axis_name"), "all_gather": (1, "axis_name"),
    "all_to_all": (1, "axis_name"), "axis_index": (0, "axis_name"),
    "axis_size": (0, "axis_name"),
    "broadcast_from": (1, "axis_name"), "all_reduce_mean": (1, "axis_name"),
    "pmax_scalar_vector": (1, "axis_name"),
}

_MESH_CANONICAL = ("dp", "tp", "sp", "pp", "ep")


def module_name_for(path: str) -> str:
    """Dotted module name for a file, walking up through __init__.py
    packages ('cpd_tpu.parallel.ring'; bare stem for scripts)."""
    path = os.path.abspath(path)
    stem = os.path.splitext(os.path.basename(path))[0]
    parts = [] if stem == "__init__" else [stem]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(parts) if parts else stem


# ---------------------------------------------------------------------------
# abstract values
# ---------------------------------------------------------------------------

def _aval(node: ast.AST, params: set, depth: int = 0) -> dict:
    """Extraction-time abstract value of an expression (module
    docstring's lattice, JSON-encoded)."""
    if depth > _AVAL_DEPTH:
        return {"k": "top"}
    if isinstance(node, ast.Constant):
        v = node.value
        if isinstance(v, bool) or v is None:
            return {"k": "const", "v": v}
        if isinstance(v, (int, float)):
            return {"k": "num", "v": v}
        if isinstance(v, str):
            return {"k": "str", "v": v}
        return {"k": "top"}
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _aval(node.operand, params, depth + 1)
        if inner.get("k") == "num":
            return {"k": "num", "v": -inner["v"]}
        return {"k": "top"}
    if isinstance(node, (ast.Tuple, ast.List)):
        if len(node.elts) > 16:
            return {"k": "top"}
        return {"k": "tuple",
                "v": [_aval(el, params, depth + 1) for el in node.elts]}
    if isinstance(node, ast.Name):
        kind = "param" if node.id in params else "name"
        return {"k": kind, "v": node.id}
    if isinstance(node, ast.Attribute):
        chain = dotted_name(node)
        if chain:
            return {"k": "attr", "v": chain.split(".")}
        return {"k": "top"}
    if isinstance(node, ast.JoinedStr):
        return {"k": "fstr"}
    if isinstance(node, ast.Starred):
        return {"k": "star"}
    if isinstance(node, ast.Call):
        callee = dotted_name(node.func)
        return {
            "k": "call", "f": callee,
            "args": [_aval(a, params, depth + 1) for a in node.args],
            "kw": {k.arg: _aval(k.value, params, depth + 1)
                   for k in node.keywords if k.arg is not None},
        }
    return {"k": "top"}


# ---------------------------------------------------------------------------
# permutation bijection analysis (collective-contract's local half,
# computed at extraction so cached summaries carry the verdict)
# ---------------------------------------------------------------------------

def _linear_in(expr: ast.AST, var: str, consts: dict) -> Optional[tuple]:
    """Classify `expr` as an injective-mod-w function of `var`: returns
    (stride, ...) marker when provably injective over range(w), None when
    unknown, and raises nothing.  Recognized: i, i+c, i-c, c-i, w-1-i,
    (any of those) % w, with c an int literal/module constant."""
    node = expr
    if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod)):
        node = node.left           # (f(i)) % w is injective iff f is
    if isinstance(node, ast.Name) and node.id == var:
        return (1,)
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add,
                                                            ast.Sub)):
        left_has = any(isinstance(n, ast.Name) and n.id == var
                       for n in ast.walk(node.left))
        right_has = any(isinstance(n, ast.Name) and n.id == var
                        for n in ast.walk(node.right))
        if left_has and not right_has:
            inner = _linear_in(node.left, var, consts)
            return inner
        if right_has and not left_has:
            inner = _linear_in(node.right, var, consts)
            if inner is None or inner[0] == "noninj":
                return inner   # c - 2*i is as non-injective as 2*i
            # c - i is injective; c + i too
            return (-inner[0],) if isinstance(node.op, ast.Sub) else inner
    if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult)):
        # stride * i: injective mod w only when gcd(stride, w) == 1 —
        # unknowable without w, so treat literal strides != 1 as suspect
        for side, other in ((node.left, node.right),
                            (node.right, node.left)):
            if isinstance(side, ast.Name) and side.id == var:
                c = literal_int(other)
                if c is None and isinstance(other, ast.Name):
                    c = consts.get(other.id)
                if c is not None and abs(c) != 1:
                    return ("noninj", c)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.FloorDiv):
        left_has = any(isinstance(n, ast.Name) and n.id == var
                       for n in ast.walk(node.left))
        if left_has:
            return ("noninj", "//")
    return None


def _perm_violation(perm_node: ast.AST, consts: dict) -> Optional[str]:
    """A message when the ppermute permutation expression is provably NOT
    a bijection; None when it is one or is unresolvable."""
    # literal [(s, d), ...]
    if isinstance(perm_node, (ast.List, ast.Tuple)) and perm_node.elts:
        srcs, dsts = [], []
        for el in perm_node.elts:
            if not (isinstance(el, ast.Tuple) and len(el.elts) == 2):
                return None
            s, d = (literal_int(el.elts[0]), literal_int(el.elts[1]))
            if s is None or d is None:
                return None
            srcs.append(s)
            dsts.append(d)
        if len(set(srcs)) != len(srcs):
            return (f"permutation repeats source rank(s) "
                    f"{sorted(s for s in srcs if srcs.count(s) > 1)} — "
                    f"ppermute silently drops duplicate senders")
        if len(set(dsts)) != len(dsts):
            return (f"permutation repeats destination rank(s) "
                    f"{sorted(d for d in dsts if dsts.count(d) > 1)} — "
                    f"colliding receivers make the result rank-dependent")
        return None
    # [(f(i), g(i)) for i in range(w)]
    if isinstance(perm_node, ast.ListComp) and len(
            perm_node.generators) == 1:
        gen = perm_node.generators[0]
        if not (isinstance(gen.target, ast.Name)
                and isinstance(gen.iter, ast.Call)
                and base_name(gen.iter.func) == "range"
                and not gen.ifs):
            return None
        var = gen.target.id
        elt = perm_node.elt
        if not (isinstance(elt, ast.Tuple) and len(elt.elts) == 2):
            return None
        for label, half in (("source", elt.elts[0]),
                            ("destination", elt.elts[1])):
            uses_var = any(isinstance(n, ast.Name) and n.id == var
                           for n in ast.walk(half))
            if not uses_var:
                return (f"permutation {label} is constant over the "
                        f"comprehension — every rank maps to the same "
                        f"{label}; not a bijection")
            cls = _linear_in(half, var, consts)
            if cls is not None and cls[0] == "noninj":
                return (f"permutation {label} `{ast.unparse(half)}` is "
                        f"not injective over the axis (stride/floordiv "
                        f"collides ranks for even axis sizes) — "
                        f"ppermute needs a bijection")
    return None


# ---------------------------------------------------------------------------
# per-scope extraction
# ---------------------------------------------------------------------------

_JIT_NAMES = {"jax.jit", "jit"}
_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def _scope_statements(body) -> Iterator[ast.AST]:
    """Walk a scope without entering nested function/class scopes (the
    nested def node itself is yielded)."""
    stack = list(body)
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, _SCOPE_NODES):
                yield child
                continue
            stack.append(child)


def _in_pytest_raises(parents: list) -> bool:
    for p in parents:
        if isinstance(p, ast.With):
            for item in p.items:
                ctx = item.context_expr
                if (isinstance(ctx, ast.Call)
                        and base_name(ctx.func) == "raises"):
                    return True
    return False


class _ScopeExtractor:
    """Extract one function scope's facts (module docstring)."""

    def __init__(self, name: str, qual: str, node, body,
                 int_consts: dict, lineno: int):
        self.name = name
        self.qual = qual
        self.int_consts = int_consts
        self._perm_sources = _extract_perm_sources(body)
        params: list = []
        kwonly: list = []
        if node is not None and not isinstance(node, ast.Module):
            a = node.args
            params = [p.arg for p in (a.posonlyargs + a.args)]
            kwonly = [p.arg for p in a.kwonlyargs]
        self.params = params
        self.kwonly = kwonly
        self.pset = set(params) | set(kwonly)
        self.out = {
            "name": name, "qual": qual, "line": lineno,
            "params": params, "kwonly": kwonly,
            "calls": [], "refs": [], "assigns": {},
            "axis_literals": [], "perm_findings": [],
            "kahan_unpacks": [], "wire_payloads": [],
            "jit_in_loop": [], "table_subscripts": [],
            "supervisor_objs": {}, "jit_tables": [], "returns": [],
        }
        self._assign_deps: dict = {}       # name -> set of RHS names
        self._refs: set = set()
        self._walk(body, parents=[])
        self._close_wire_payloads()
        self.out["refs"] = sorted(self._refs)[:200]

    # -- traversal ---------------------------------------------------------

    def _walk(self, body, parents):
        for stmt in body:
            self._visit(stmt, parents)

    def _visit(self, node, parents):
        if isinstance(node, _SCOPE_NODES):
            return                          # nested scope: its own summary
        if isinstance(node, ast.Assign):
            self._handle_assign(node, parents)
        elif isinstance(node, ast.Return) and node.value is not None:
            self.out["returns"].append(_aval(node.value, self.pset))
        self._scan_expressions(node, parents)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPE_NODES):
                continue
            self._visit(child, parents + [node])

    def _scan_expressions(self, node, parents):
        if isinstance(node, ast.Call):
            self._handle_call(node, parents)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            self._refs.add(node.id)
        elif isinstance(node, ast.Subscript):
            self._handle_subscript(node, parents)

    # -- statement handlers ------------------------------------------------

    def _handle_assign(self, node: ast.Assign, parents):
        value = node.value
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            av = _aval(value, self.pset)
            prev = self.out["assigns"].get(tgt)
            # joined local assignment view: two different AVals -> top
            self.out["assigns"][tgt] = av if prev in (None, av) else \
                {"k": "top"}
            self._assign_deps.setdefault(tgt, set()).update(
                n.id for n in ast.walk(value)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load))
            if isinstance(value, ast.Call):
                cname = base_name(value.func)
                if cname.endswith("TransportSupervisor"):
                    self.out["supervisor_objs"][tgt] = "transport"
                elif cname.endswith("PrecisionSupervisor"):
                    self.out["supervisor_objs"][tgt] = "precision"
            if isinstance(value, (ast.Dict,)) or (
                    isinstance(value, ast.Call)
                    and base_name(value.func) == "dict"
                    and not value.args):
                self.out["jit_tables"].append(
                    {"name": tgt, "jit": False, "line": node.lineno})
        # res, comp = kahanish(...)
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Tuple)
                and len(node.targets[0].elts) == 2
                and all(isinstance(e, ast.Name)
                        for e in node.targets[0].elts)
                and isinstance(value, ast.Call)):
            res, comp = (e.id for e in node.targets[0].elts)
            self.out["kahan_unpacks"].append({
                "res": res, "comp": comp,
                "callee": dotted_name(value.func), "line": node.lineno})
        # table[key] = jax.jit(...)  — mark jit tables
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)
                and isinstance(node.targets[0].value, ast.Name)):
            tname = node.targets[0].value.id
            has_jit = any(isinstance(n, ast.Call)
                          and dotted_name(n.func) in _JIT_NAMES
                          for n in ast.walk(value))
            if has_jit:
                for t in self.out["jit_tables"]:
                    if t["name"] == tname:
                        t["jit"] = True
                        break
                else:
                    self.out["jit_tables"].append(
                        {"name": tname, "jit": True, "line": node.lineno})

    def _handle_call(self, node: ast.Call, parents):
        callee = dotted_name(node.func)
        fact = {
            "callee": callee, "line": node.lineno, "col": node.col_offset,
            "args": [_aval(a, self.pset) for a in node.args],
            "kw": {k.arg: _aval(k.value, self.pset)
                   for k in node.keywords if k.arg is not None},
            "star": any(isinstance(a, ast.Starred) for a in node.args)
                    or any(k.arg is None for k in node.keywords),
            "raises_ctx": _in_pytest_raises(parents),
        }
        self.out["calls"].append(fact)
        short = base_name(node.func)
        # collective axis literals + ppermute permutation analysis
        spec = COLLECTIVES.get(short)
        if spec is not None:
            axis_arg = call_arg(node, spec[0], spec[1])
            if axis_arg is not None:
                lits = []
                if (isinstance(axis_arg, ast.Constant)
                        and isinstance(axis_arg.value, str)):
                    lits = [axis_arg]
                elif isinstance(axis_arg, (ast.Tuple, ast.List)):
                    lits = [el for el in axis_arg.elts
                            if isinstance(el, ast.Constant)
                            and isinstance(el.value, str)]
                for lit in lits:
                    self.out["axis_literals"].append({
                        "collective": short, "axis": lit.value,
                        "line": lit.lineno, "col": lit.col_offset})
        if short == "ppermute":
            perm_arg = call_arg(node, 2, "perm")
            if perm_arg is not None:
                msg = None
                if isinstance(perm_arg, ast.Name):
                    # a perm built earlier in the scope: analyze its RHS
                    src = self._perm_sources.get(perm_arg.id)
                    if src is not None:
                        msg = _perm_violation(src, self.int_consts)
                else:
                    msg = _perm_violation(perm_arg, self.int_consts)
                if msg:
                    self.out["perm_findings"].append({
                        "line": perm_arg.lineno, "col": perm_arg.col_offset,
                        "msg": msg})
        if short in ("ppermute", "all_gather") and node.args:
            self.out["wire_payloads"].append({
                "collective": short,
                "names": sorted({n.id for n in ast.walk(node.args[0])
                                 if isinstance(n, ast.Name)
                                 and isinstance(n.ctx, ast.Load)}),
                "line": node.lineno, "col": node.col_offset})
        # jit construction inside a loop without a memoization guard.
        # A `for cfg in (a, b)` sweep over a SMALL literal tuple is a
        # bounded set of distinct once-traced configs, not a retrace
        # hazard — only while-loops / unbounded iterables flag.
        if callee in _JIT_NAMES:
            loops = [p for p in parents
                     if isinstance(p, (ast.For, ast.While))]
            hazardous = any(
                isinstance(p, ast.While)
                or not (isinstance(p.iter, (ast.Tuple, ast.List))
                        and len(p.iter.elts) <= 4)
                for p in loops)
            if loops and hazardous:
                guarded = any(
                    isinstance(p, ast.If)
                    and isinstance(p.test, ast.Compare)
                    and any(isinstance(op, ast.NotIn)
                            for op in p.test.ops)
                    for p in parents)
                if not guarded:
                    self.out["jit_in_loop"].append(
                        {"line": node.lineno, "col": node.col_offset})

    def _handle_subscript(self, node: ast.Subscript, parents):
        if not (isinstance(node.value, ast.Name)
                and isinstance(node.ctx, ast.Load)):
            return
        key = node.slice
        entry = {"table": node.value.id, "line": node.lineno,
                 "col": node.col_offset, "key_kind": "other",
                 "key_obj": "", "key_attr": "", "key_callee": ""}
        if isinstance(key, ast.Attribute):
            chain = dotted_name(key)
            parts = chain.split(".") if chain else []
            if len(parts) == 2:
                entry.update(key_kind="attr", key_obj=parts[0],
                             key_attr=parts[1])
        elif isinstance(key, ast.JoinedStr):
            entry["key_kind"] = "fstr"
        elif isinstance(key, ast.Call):
            entry.update(key_kind="call",
                         key_callee=dotted_name(key.func))
        elif isinstance(key, ast.Name):
            entry["key_kind"] = "name"
            src = self.out["assigns"].get(key.id)
            if src is not None:
                if src.get("k") == "fstr":
                    entry["key_kind"] = "fstr"
                elif src.get("k") == "attr" and len(src["v"]) == 2:
                    entry.update(key_kind="attr", key_obj=src["v"][0],
                                 key_attr=src["v"][1])
                elif src.get("k") == "call":
                    entry.update(key_kind="call", key_callee=src["f"])
        elif isinstance(key, ast.Constant):
            entry["key_kind"] = "const"
        self.out["table_subscripts"].append(entry)

    # -- post-passes -------------------------------------------------------

    def _close_wire_payloads(self):
        """Transitive closure of payload names through scope-local
        assignments, so `wire = to_wire(res, comp); ppermute(wire, ...)`
        sees res/comp in the payload's name set."""
        for wp in self.out["wire_payloads"]:
            seen = set(wp["names"])
            frontier = list(seen)
            for _ in range(20):
                nxt = set()
                for nm in frontier:
                    nxt |= self._assign_deps.get(nm, set()) - seen
                if not nxt:
                    break
                seen |= nxt
                frontier = list(nxt)
            wp["names"] = sorted(seen)[:80]


def _extract_perm_sources(body) -> dict:
    """name -> the list-comp/list expression assigned to it in this
    scope (for `perm = [...]; ppermute(x, a, perm)`)."""
    out = {}
    for n in _scope_statements(body):
        if (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, (ast.List, ast.Tuple,
                                         ast.ListComp))):
            out[n.targets[0].id] = n.value
    return out


def _declared_axes_of(tree: ast.Module) -> list:
    """Mesh axes declared anywhere in the module (the axis-name rule's
    binding logic, shared)."""
    from .core import string_literals
    declared: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = base_name(node.func)
        if name in ("make_mesh", "data_parallel_mesh"):
            declared |= set(_MESH_CANONICAL)
        elif name == "Mesh":
            axes = call_arg(node, 1, "axis_names")
            if axes is not None:
                declared |= {c.value for c in string_literals(axes)}
        elif dotted_name(node.func) in ("jax.make_mesh", "make_mesh2"):
            axes = call_arg(node, 1, "axis_names")
            if axes is not None:
                declared |= {c.value for c in string_literals(axes)}
        elif name in ("PartitionSpec", "P"):
            declared |= {c.value for c in string_literals(node)}
        elif name in ("shard_map", "pjit"):
            for kw in node.keywords:
                if kw.arg in ("in_specs", "out_specs", "axis_names"):
                    declared |= {c.value for c in string_literals(kw.value)}
    return sorted(declared)


def summarize_module(path: str, src: str, tree: ast.Module,
                     modname: Optional[str] = None) -> dict:
    """The serializable whole-program facts of one parsed file."""
    modname = modname or module_name_for(path)
    int_consts: dict = {}
    consts: dict = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            av = _aval(node.value, set())
            consts[node.targets[0].id] = av
            iv = literal_int(node.value)
            if iv is not None:
                int_consts[node.targets[0].id] = iv

    imports: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else \
                    alias.name.split(".")[0]
                imports[local] = {"kind": "mod", "mod": target}
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if node.level:
                pkg = modname.split(".")
                if not path.endswith("__init__.py"):
                    pkg = pkg[:-1]
                pkg = pkg[:len(pkg) - (node.level - 1)] if node.level > 1 \
                    else pkg
                mod = ".".join(pkg + ([mod] if mod else []))
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                imports[local] = {"kind": "obj", "mod": mod,
                                  "attr": alias.name}

    functions: dict = {}

    def visit_scope(node, qual_prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = (qual_prefix + "." + child.name) if qual_prefix \
                    else child.name
                ex = _ScopeExtractor(child.name, qual, child, child.body,
                                     int_consts, child.lineno)
                functions[qual] = ex.out
                visit_scope(child, qual)
            elif isinstance(child, ast.ClassDef):
                visit_scope(child, (qual_prefix + "." + child.name)
                            if qual_prefix else child.name)
            else:
                visit_scope(child, qual_prefix)

    # module-level pseudo-scope
    mod_ex = _ScopeExtractor("<module>", "<module>", None, tree.body,
                             int_consts, 1)
    functions["<module>"] = mod_ex.out
    visit_scope(tree, "")

    return {
        "path": path, "modname": modname,
        "is_package": path.endswith("__init__.py"),
        "imports": imports,
        "declared_axes": _declared_axes_of(tree),
        "int_consts": int_consts, "consts": consts,
        "functions": functions,
    }


# ---------------------------------------------------------------------------
# the project graph
# ---------------------------------------------------------------------------

class ProjectRule(Rule):
    """Base for whole-program rules: ``check(project)`` instead of
    ``check(ctx)``."""
    scope = "project"

    def check(self, project: "ProjectGraph") -> Iterator[Finding]:
        raise NotImplementedError


class ProjectGraph:
    """Summaries indexed, linked and propagated (module docstring)."""

    def __init__(self, summaries: list):
        self.modules: dict = {}        # modname -> summary
        for s in summaries:
            key = s["modname"]
            if key in self.modules:
                # two top-level scripts with the same stem (e.g. every
                # examples/*/train.py): uniquify so neither shadows the
                # other — scripts are not import targets, so the
                # decorated name never needs to resolve
                key = s["modname"] + "@" + s["path"]
                s = dict(s, modname=key)
            self.modules[key] = s
        self.funcs: dict = {}          # (modname, qual) -> func summary
        # build from self.modules (the de-collided view), NOT from the
        # raw summaries — otherwise same-stem scripts overwrite each
        # other's functions and findings land in the wrong file
        for s in self.modules.values():
            for qual, f in s["functions"].items():
                self.funcs[(s["modname"], qual)] = f
        self._edges: dict = {}         # fkey -> set of callee fkeys
        self._redges: dict = {}        # fkey -> set of caller fkeys
        self._resolve_cache: dict = {}
        self._build_edges()
        self._env: dict = {fk: {} for fk in self.funcs}
        self._propagate()

    # -- import/function resolution ---------------------------------------

    def _module_func(self, modname: str, name: str,
                     depth: int = 0) -> Optional[tuple]:
        """(modname, qual) for a top-level function `name` of `modname`,
        chasing one level of __init__ re-exports."""
        if depth > 3:
            return None
        s = self.modules.get(modname)
        if s is None:
            return None
        if name in s["functions"]:
            return (modname, name)
        # method container classes: Class.name lookups happen elsewhere
        imp = s["imports"].get(name)
        if imp is not None:
            if imp["kind"] == "obj":
                return self._module_func(imp["mod"], imp["attr"],
                                         depth + 1)
            return None
        return None

    def resolve(self, modname: str, dotted: str) -> Optional[tuple]:
        """Resolve a (possibly dotted) callee name seen in `modname` to a
        project function key, or None (external/unresolvable)."""
        if not dotted:
            return None
        ck = (modname, dotted)
        if ck in self._resolve_cache:
            return self._resolve_cache[ck]
        out = self._resolve_uncached(modname, dotted)
        self._resolve_cache[ck] = out
        return out

    def _resolve_uncached(self, modname, dotted):
        s = self.modules.get(modname)
        if s is None:
            return None
        parts = dotted.split(".")
        head, rest = parts[0], parts[1:]
        if not rest:
            # local def, or imported object
            local = self._module_func(modname, head)
            if local is not None:
                return local
            return None
        imp = s["imports"].get(head)
        if imp is None:
            return None
        if imp["kind"] == "mod":
            target = imp["mod"]
        else:
            target = imp["mod"] + "." + imp["attr"]
        # walk remaining parts: all but the last extend the module path
        for i, part in enumerate(rest):
            is_last = i == len(rest) - 1
            if is_last:
                fn = self._module_func(target, part)
                if fn is not None:
                    return fn
                return None
            target = target + "." + part
        return None

    # -- call graph --------------------------------------------------------

    def _build_edges(self):
        for (mod, qual), f in self.funcs.items():
            edges = set()
            for call in f["calls"]:
                tgt = self.resolve(mod, call["callee"])
                if tgt is not None:
                    edges.add(tgt)
            for ref in f["refs"]:
                tgt = self.resolve(mod, ref)
                if tgt is not None:
                    edges.add(tgt)
            # an enclosing function "calls" its nested defs (they close
            # over its scope and usually run under it)
            for other_qual in self.modules[mod]["functions"]:
                if other_qual.startswith(qual + ".") and \
                        other_qual.count(".") == qual.count(".") + 1:
                    edges.add((mod, other_qual))
            edges.discard((mod, qual))
            self._edges[(mod, qual)] = edges
            for tgt in edges:
                self._redges.setdefault(tgt, set()).add((mod, qual))

    def callers(self, fkey) -> set:
        return self._redges.get(fkey, set())

    def callees(self, fkey) -> set:
        return self._edges.get(fkey, set())

    # -- lattice evaluation ------------------------------------------------

    def _concrete(self, av: dict) -> Optional[frozenset]:
        """Value set of an aval with no env needed; TOP otherwise."""
        k = av.get("k")
        if k in ("num", "str", "const"):
            return frozenset([av["v"]])
        if k == "tuple":
            parts = [self._concrete(x) for x in av["v"]]
            if any(p is None or len(p) != 1 for p in parts):
                return TOP
            return frozenset([tuple(next(iter(p)) for p in parts)])
        return TOP

    def eval_in(self, fkey, av: dict, depth: int = 0) -> Optional[frozenset]:
        """Value set of an abstract value observed inside function
        `fkey`, resolving params through the propagated environments and
        names through module constants.  None == TOP."""
        if av is None or depth > 6:
            return TOP
        k = av.get("k")
        conc = self._concrete(av)
        if conc is not None:
            return conc
        if k == "param":
            return self._env.get(fkey, {}).get(av["v"], TOP)
        if k == "name":
            mod, qual = fkey
            f = self.funcs.get(fkey)
            if f is not None:
                local = f["assigns"].get(av["v"])
                if local is not None:
                    return self.eval_in(fkey, local, depth + 1)
            cav = self.modules[mod]["consts"].get(av["v"])
            if cav is not None:
                return self.eval_in(fkey, cav, depth + 1)
            return TOP
        if k == "tuple":
            parts = [self.eval_in(fkey, x, depth + 1) for x in av["v"]]
            if any(p is TOP or len(p) != 1 for p in parts):
                return TOP
            return frozenset([tuple(next(iter(p)) for p in parts)])
        if k == "call":
            base = av.get("f", "").rsplit(".", 1)[-1]
            if base in ("pack_exmy", "pack_exmy_blocked") \
                    and len(av.get("args", [])) >= 3:
                e = self.eval_in(fkey, av["args"][1], depth + 1)
                m = self.eval_in(fkey, av["args"][2], depth + 1)
                if e is not TOP and m is not TOP and len(e) == 1 \
                        and len(m) == 1:
                    fmt = (next(iter(e)), next(iter(m)))
                    if base == "pack_exmy":
                        return frozenset([("packed", fmt)])
                    # blocked wire: the marker carries the block size
                    # too — ("packed", fmt, block) — so format-flow can
                    # lint pack/unpack BLOCK drift, not just format
                    # drift (a mismatched block re-slices the sidecar
                    # lane at the wrong offsets, bitwise-silently)
                    bav = (av["args"][3] if len(av["args"]) >= 4
                           else av.get("kw", {}).get("block_size"))
                    b = (self.eval_in(fkey, bav, depth + 1)
                         if bav is not None else TOP)
                    if b is not TOP and len(b) == 1 \
                            and isinstance(next(iter(b)), int):
                        return frozenset([("packed", fmt,
                                           next(iter(b)))])
                return TOP
            tgt = self.resolve(fkey[0], av.get("f", ""))
            if tgt is not None:
                return self.returns_of(tgt, depth + 1)
        return TOP

    def returns_of(self, fkey, depth: int = 0) -> Optional[frozenset]:
        """Joined return-value set of a function (TOP unless every
        return is concrete under its env)."""
        if depth > 6:
            return TOP
        f = self.funcs.get(fkey)
        if f is None or not f["returns"]:
            return TOP
        out = set()
        for rav in f["returns"]:
            vs = self.eval_in(fkey, rav, depth + 1)
            if vs is TOP:
                return TOP
            out |= vs
            if len(out) > _WIDEN_CAP:
                return TOP
        return frozenset(out)

    # -- interprocedural parameter propagation ----------------------------

    def _propagate(self):
        for _ in range(_PROPAGATE_ROUNDS):
            changed = False
            for (mod, qual), f in self.funcs.items():
                for call in f["calls"]:
                    tgt = self.resolve(mod, call["callee"])
                    if tgt is None or call["star"]:
                        continue
                    tf = self.funcs[tgt]
                    bindings = list(zip(tf["params"], call["args"]))
                    for kname, kav in call["kw"].items():
                        if kname in tf["params"] or kname in tf["kwonly"]:
                            bindings.append((kname, kav))
                    env = self._env[tgt]
                    for pname, pav in bindings:
                        vs = self.eval_in((mod, qual), pav)
                        old = env.get(pname, frozenset())
                        if old is TOP:
                            continue
                        new = TOP if vs is TOP else old | vs
                        if new is not TOP and len(new) > _WIDEN_CAP:
                            new = TOP
                        if new != old:
                            env[pname] = new
                            changed = True
            if not changed:
                break

    def param_values(self, fkey, pname) -> Optional[frozenset]:
        vs = self._env.get(fkey, {}).get(pname)
        return TOP if vs is None or vs is TOP else vs

    # -- reachability helpers ---------------------------------------------

    def reachable_axes(self, fkey) -> set:
        """Axes declared in this function's own module or in any module
        holding a transitive caller — 'a mesh constructor that actually
        reaches it through the call graph'."""
        axes: set = set()
        seen = {fkey}
        frontier = [fkey]
        while frontier:
            cur = frontier.pop()
            axes.update(self.modules[cur[0]]["declared_axes"])
            for caller in self.callers(cur):
                if caller not in seen:
                    seen.add(caller)
                    frontier.append(caller)
        return axes

    def ring_reaching(self, fkey, max_depth: int = 8,
                      root_bindings: Optional[dict] = None
                      ) -> Optional[int]:
        """Line of the first ring sink (a call with mode='ring', a
        ring_quantized_sum call, or a pack_exmy call) reachable from
        `fkey` through the call graph; None when no sink is reachable.

        ``root_bindings`` (param -> value set) overrides the JOINED
        parameter environment for `fkey` itself — one level of context
        sensitivity, so a ladder handed to ``f(..., mode="faithful")``
        is not condemned because a DIFFERENT call site passes
        ``mode="ring"`` through the same function."""
        seen = {fkey}
        frontier = [(fkey, 0)]
        while frontier:
            cur, d = frontier.pop()
            f = self.funcs.get(cur)
            if f is not None:
                for call in f["calls"]:
                    base = call["callee"].rsplit(".", 1)[-1]
                    if base in ("ring_quantized_sum", "pack_exmy"):
                        return call["line"]
                    mode = call["kw"].get("mode")
                    if mode is not None:
                        if (cur == fkey and root_bindings is not None
                                and mode.get("k") == "param"
                                and mode["v"] in root_bindings):
                            vs = root_bindings[mode["v"]]
                        else:
                            vs = self.eval_in(cur, mode)
                        if vs is not TOP and "ring" in vs:
                            return call["line"]
            if d >= max_depth:
                continue
            for nxt in self.callees(cur):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append((nxt, d + 1))
        return None

    def kahan_producing(self, modname: str, callee: str,
                        depth: int = 2) -> bool:
        """True when `callee` (as seen from `modname`) is a Kahan
        accumulator by name, or transitively calls one within `depth`."""
        if "kahan" in callee.lower():
            return True
        tgt = self.resolve(modname, callee)
        seen = set()
        frontier = [(tgt, 0)] if tgt is not None else []
        while frontier:
            cur, d = frontier.pop()
            if cur is None or cur in seen or d > depth:
                continue
            seen.add(cur)
            if "kahan" in cur[1].lower():
                return True
            f = self.funcs.get(cur)
            if f is None:
                continue
            for call in f["calls"]:
                if "kahan" in call["callee"].lower():
                    return True
                nxt = self.resolve(cur[0], call["callee"])
                if nxt is not None and nxt not in seen:
                    frontier.append((nxt, d + 1))
        return False

    # -- iteration ---------------------------------------------------------

    def iter_functions(self):
        """(fkey, func summary, module summary) for every scope."""
        for fkey, f in self.funcs.items():
            yield fkey, f, self.modules[fkey[0]]
