"""The cached analysis driver behind the CLI: per-file fingerprint cache
+ whole-program pass + config filtering + git ``--changed-only`` mode.

``lint_tree`` (core.py) is the simple always-parse API the tests lean
on; ``run_analysis`` is the production entry — same rules, same
findings, but files whose fingerprint matches the cache are served
without re-parsing (their module-rule findings AND their project-layer
summaries come from disk), and the result carries the counters the
cache-correctness test pins (``files_parsed == 0`` on a warm unchanged
tree).
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
from typing import Iterable, Optional

from . import core
from .cache import DEFAULT_CACHE_DIR, LintCache
from .config import Config, load_config
from .core import Finding, LintError

__all__ = ["AnalysisResult", "run_analysis", "changed_files",
           "DEFAULT_CACHE_DIR"]


@dataclasses.dataclass
class AnalysisResult:
    findings: list
    files_checked: int
    files_parsed: int        # cache misses; 0 on a warm unchanged tree
    config: Config
    # program (IR) scope — populated only when run with ir=True
    programs_checked: int = 0
    programs_traced: int = 0   # IR cache misses; 0 on a warm tree
    trace_failures: int = 0    # nonzero -> the gate is DOWN (exit 2)


def changed_files(paths: Iterable[str],
                  since: Optional[str] = None) -> list[str]:
    """The .py files under `paths` that git reports as changed: working
    tree + index vs HEAD (``git status --porcelain``), or the diff
    against ``since`` (a ref; CI passes the PR base).  A broken git
    environment is a loud LintError (exit 2) — silently linting nothing
    would shrink the gate to zero coverage."""
    roots = [os.path.abspath(p) for p in paths]
    cwd = roots[0] if roots else os.getcwd()
    if os.path.isfile(cwd):
        cwd = os.path.dirname(cwd)
    if since:
        cmd = ["git", "diff", "--name-only", "--diff-filter=d", "-z",
               since, "--"]
    else:
        # -uall lists FILES inside untracked directories (plain
        # --porcelain emits only "?? newdir/", which would silently
        # skip every new file in a new package)
        cmd = ["git", "status", "--porcelain", "-uall", "-z"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              cwd=cwd, timeout=60)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise LintError(f"--changed-only: cannot run git: {e}") from e
    if proc.returncode != 0:
        raise LintError(f"--changed-only: git failed: "
                        f"{proc.stderr.strip() or proc.stdout.strip()}")
    top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                         capture_output=True, text=True, cwd=cwd)
    repo = top.stdout.strip() if top.returncode == 0 else cwd
    names: list[str] = []
    chunks = [c for c in proc.stdout.split("\0") if c]
    i = 0
    while i < len(chunks):
        chunk = chunks[i]
        i += 1
        if since:
            name = chunk
        else:
            status, name = chunk[:2], chunk[3:]
            if status and status[0] in "RC":
                # -z rename/copy records emit the OLD path as the NEXT
                # NUL field, with no status prefix — consume it so it
                # is neither prefix-sliced nor linted (it no longer
                # exists under that name)
                i += 1
        if name.endswith(".py"):
            names.append(os.path.join(repo, name))
    wanted = []
    for name in names:
        full = os.path.abspath(name)
        if not os.path.isfile(full):
            continue
        if any(full == r or full.startswith(r + os.sep) for r in roots):
            wanted.append(full)
    return sorted(set(wanted))


def run_analysis(paths: Iterable[str],
                 select: Optional[Iterable[str]] = None,
                 config_path: Optional[str] = None,
                 use_cache: bool = True,
                 cache_dir: Optional[str] = None,
                 changed_only: bool = False,
                 since: Optional[str] = None,
                 ir: bool = False,
                 ir_providers=None) -> AnalysisResult:
    """The CLI's analysis pipeline (module docstring).

    ``ir=True`` additionally runs the program-contract scope
    (analysis/ir/): the registered compiled programs are traced to
    jaxprs (fact-cached under the same cache dir) and the ir-* rules
    check their declared contracts.  The ONLY mode that imports jax.
    ``ir_providers`` overrides the registry source (fixture registries
    in tests)."""
    paths = list(paths)
    config = load_config(paths, cli_path=config_path)
    if changed_only:
        files = changed_files(paths, since=since)
    else:
        files = list(core.iter_python_files(paths))
    cache = None
    if use_cache:
        cache = LintCache(cache_dir or DEFAULT_CACHE_DIR,
                          sorted(core.all_rules()),
                          config_fingerprint=config.fingerprint())
    findings: list[Finding] = []
    summaries: list[dict] = []
    parsed = 0
    for path in files:
        entry = cache.get(path) if cache is not None else None
        if entry is not None:
            local, summary = entry
        else:
            try:
                with open(path, encoding="utf-8") as fh:
                    src = fh.read()
            except OSError as e:
                raise LintError(f"cannot read {path}: {e}") from e
            import ast as _ast
            try:
                tree = _ast.parse(src, filename=path)
            except SyntaxError as e:
                raise LintError(f"{path}: syntax error at line "
                                f"{e.lineno}: {e.msg}") from e
            parsed += 1
            # cache entries always hold the FULL rule set's findings
            # (select filtering happens below), so a --select run can
            # never poison the cache for a later full run
            local, summary = core.lint_parsed(path, src, tree,
                                              select=None)
            if cache is not None:
                cache.put(path, local, summary)
        findings.extend(local)
        summaries.append(summary)
    findings.extend(core.run_project_rules(summaries, select=select))
    programs_checked = programs_traced = trace_failures = 0
    if ir:
        from .ir.run import run_ir
        from .ir.registry import DEFAULT_PROVIDERS
        ir_result = run_ir(
            select=select,
            providers=(ir_providers if ir_providers is not None
                       else DEFAULT_PROVIDERS),
            use_cache=use_cache,
            cache_dir=cache_dir or DEFAULT_CACHE_DIR,
            extra_fingerprint=config.fingerprint())
        findings.extend(ir_result.findings)
        programs_checked = ir_result.programs_checked
        programs_traced = ir_result.programs_traced
        trace_failures = ir_result.trace_failures
    if select is not None:
        wanted = set(select)
        findings = [f for f in findings if f.rule in wanted]
    findings = [f for f in findings
                if not config.exempts(f.rule, f.path)]
    return AnalysisResult(findings=sorted(findings),
                          files_checked=len(files),
                          files_parsed=parsed, config=config,
                          programs_checked=programs_checked,
                          programs_traced=programs_traced,
                          trace_failures=trace_failures)
