"""Per-file fingerprint cache so the whole-program gate stays tier-1
fast: a warm re-run over an unchanged tree re-parses ZERO files.

One JSON entry per source file under ``.cpd-lint-cache/`` (CWD by
default; ``--cache-dir`` overrides, ``--no-cache`` bypasses).  The entry
key is the sha1 of the absolute path; the entry is valid only while its
**fingerprint** matches:

    (mtime_ns, size, rule-set hash)

The rule-set hash covers the sorted rule ids, ``SCHEMA_VERSION`` — bump
the version whenever extraction or a rule's logic changes shape, so
stale caches self-invalidate instead of silently serving old facts —
AND the resolved config's fingerprint (``Config.fingerprint``, ISSUE
14): exemptions are applied after the cache, but the config also picks
the roots and is the policy every cached verdict was produced under, so
editing pyproject's [tool.cpd-lint] table invalidates warm runs
wholesale (regression-pinned) rather than leaving any path where a
policy edit is silently served stale.

An entry stores the module-rule findings (already suppression-filtered —
suppressions live in the file, so the fingerprint covers them) and the
serialized module summary (analysis/project.py), which is everything the
project rules need.  Corrupt or unreadable entries are treated as
misses, never errors — the cache is an accelerator, not a source of
truth.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

from .core import Finding

__all__ = ["LintCache", "SCHEMA_VERSION", "ruleset_hash",
           "DEFAULT_CACHE_DIR"]

# the ONE home of the default cache location (engine.py re-exports it;
# the IR fact cache nests under it as <dir>/ir/)
DEFAULT_CACHE_DIR = ".cpd-lint-cache"

# bump on ANY change to summary extraction, Finding shape, or rule logic
# that could alter cached results for an unchanged file
# (5: the host scope — per-file cached findings now include host rules)
SCHEMA_VERSION = 5


def ruleset_hash(rule_ids, config_fingerprint: str = "") -> str:
    blob = json.dumps([SCHEMA_VERSION, sorted(rule_ids),
                       config_fingerprint])
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def _fingerprint(path: str, rules_hash: str) -> Optional[list]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return [st.st_mtime_ns, st.st_size, rules_hash]


class LintCache:
    """Directory-backed per-file cache (module docstring)."""

    def __init__(self, directory: str, rule_ids,
                 config_fingerprint: str = ""):
        self.directory = directory
        self.rules_hash = ruleset_hash(rule_ids, config_fingerprint)
        self.hits = 0
        self.misses = 0

    def _entry_path(self, path: str) -> str:
        key = hashlib.sha1(os.path.abspath(path).encode()).hexdigest()
        return os.path.join(self.directory, key + ".json")

    def get(self, path: str) -> Optional[tuple]:
        """(findings, summary) when fresh; None on miss/stale."""
        fp = _fingerprint(path, self.rules_hash)
        if fp is None:
            return None
        try:
            with open(self._entry_path(path), encoding="utf-8") as fh:
                entry = json.load(fh)
        except (OSError, ValueError):
            return None
        if entry.get("fingerprint") != fp:
            return None
        try:
            findings = [Finding(**f) for f in entry["findings"]]
            summary = entry["summary"]
        except (KeyError, TypeError):
            return None
        self.hits += 1
        return findings, summary

    def put(self, path: str, findings, summary) -> None:
        self.misses += 1
        fp = _fingerprint(path, self.rules_hash)
        if fp is None:
            return
        entry = {"fingerprint": fp,
                 "findings": [f.to_dict() for f in findings],
                 "summary": summary}
        try:
            os.makedirs(self.directory, exist_ok=True)
            tmp = self._entry_path(path) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(entry, fh)
            os.replace(tmp, self._entry_path(path))
        except OSError:
            # a read-only checkout must still lint; the cache silently
            # degrades to a no-op there
            pass
