"""The program registry: where subsystems declare contract-bearing
compiled programs for the jaxpr-level rules.

A declaration is a `ProgramSpec`: a ``build()`` thunk returning
``(fn, args)`` — ``fn`` is traced with ``jax.make_jaxpr(fn)(*args)``
(args are ``ShapeDtypeStruct``s; nothing executes) — plus the program's
CONTRACTS (which rules gate it) and its source ``deps`` (the modules
whose edits invalidate its cached facts).  Subsystem modules export an
``ir_programs(reg)`` function; `collect_programs` imports the provider
list and gathers every declaration.  Import stays stdlib-only — jax is
touched only inside ``build()`` at trace time (trace.py).

Contracts a spec can claim (each enforced by one rule in rules.py):

``twin``           bitwise-parity twin group: every program sharing the
                   group id must move the IDENTICAL multiset of
                   transport collectives (kind, axes, payload
                   dtype/shape, trip count) — `ir-schedule`.
``wire``           zero-arg thunk returning the analytic transport-byte
                   expectation (``ring_transport_bytes`` & co); the
                   jaxpr-counted bytes must equal it — `ir-wire-ledger`.
``bitwise``        the program is bitwise-gated (claims cross-program
                   bit reproducibility somewhere in the suite): no
                   ulp-unstable primitive may appear outside the blessed
                   exact helpers — `ir-bitwise`.
``overlap``        expected interleaving verdict (True: transport
                   collectives must interleave with compute; False:
                   must strictly postdate it) — `ir-overlap`.
``retrace_group`` / ``retrace_key``
                   programs in one group are entries of one StepTable
                   family; two members with DISTINCT traced programs
                   must carry distinct keys (the PR 5 half-keyed
                   StepTable bug, verified dynamically) — `ir-retrace`.
``axis_sizes``     mesh axis name -> size, needed to price all_gather /
                   all_to_all wire bytes per device.
"""

from __future__ import annotations

import dataclasses
import importlib
import importlib.util
import os
import sys
from typing import Callable, Optional

__all__ = ["ProgramSpec", "ProgramRegistry", "collect_programs",
           "DEFAULT_PROVIDERS", "ensure_cpu_devices", "IR_WORLD"]

# the virtual CPU mesh every declaration sizes against (conftest.py's
# device count; ensure_cpu_devices forces it for the bare CLI)
IR_WORLD = 8

# provider modules collect_programs imports by default — each exports
# ir_programs(reg).  Order is the report order.
DEFAULT_PROVIDERS = (
    "cpd_tpu.parallel.reduction",
    "cpd_tpu.parallel.ring",
    "cpd_tpu.parallel.overlap",
    "cpd_tpu.parallel.zero",
    "cpd_tpu.linalg.blockmm",
    "cpd_tpu.linalg.qr",
    "cpd_tpu.linalg.eigen",
    "cpd_tpu.train.step",
    "cpd_tpu.train.lm",
    "cpd_tpu.serve.model",
)


def ensure_cpu_devices(n: int = IR_WORLD) -> None:
    """Force an n-device virtual CPU platform, BEFORE jax initializes.

    A no-op when jax is already imported (pytest's conftest.py has
    already done this; a host that imported jax with fewer devices will
    surface per-program trace failures instead — the honesty path).
    Beyond the env vars, the platform is ALSO pinned through
    ``jax.config`` — experimental PJRT plugins (the axon TPU plugin)
    override the `JAX_PLATFORMS` env var, and the config update is the
    forcing that sticks (the same double conftest.py does)."""
    if "jax" in sys.modules:
        return
    import re
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   flags)
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One declared contract-bearing program (module docstring)."""
    name: str
    build: Callable                       # () -> (fn, args_tuple)
    deps: tuple = ()                      # dotted module names
    axis_sizes: Optional[dict] = None     # {axis_name: size}
    twin: Optional[str] = None
    wire: Optional[Callable] = None       # () -> expected bytes
    bitwise: bool = False
    allow_unstable: tuple = ()            # blessed prim names + reasons
    overlap: Optional[bool] = None
    retrace_group: Optional[str] = None
    retrace_key: Optional[object] = None  # hashable; required with group
    origin: tuple = ("<unknown>", 1)      # (path, line) of the declare

    def __post_init__(self):
        if self.retrace_group is not None and self.retrace_key is None:
            raise ValueError(
                f"program {self.name!r}: retrace_group without a "
                f"retrace_key — the probe compares keys, a keyless "
                f"member would be unverifiable")


class ProgramRegistry:
    """Ordered, name-unique collection of ProgramSpecs."""

    def __init__(self):
        self.specs: list[ProgramSpec] = []
        self._names: set[str] = set()

    def declare(self, name: str, build: Callable, **kw) -> ProgramSpec:
        if name in self._names:
            raise ValueError(f"duplicate program name {name!r}")
        if "origin" not in kw:
            f = sys._getframe(1)
            kw["origin"] = (f.f_code.co_filename, f.f_lineno)
        spec = ProgramSpec(name=name, build=build, **kw)
        self._names.add(name)
        self.specs.append(spec)
        return spec


def _import_provider(entry: str):
    """A provider is a dotted module name or a .py file path (fixture
    registries in tests)."""
    if entry.endswith(".py") or os.sep in entry:
        path = os.path.abspath(entry)
        mod_name = "_cpd_ir_provider_" + os.path.basename(path)[:-3]
        ispec = importlib.util.spec_from_file_location(mod_name, path)
        if ispec is None or ispec.loader is None:
            raise ImportError(f"cannot load provider file {entry}")
        mod = importlib.util.module_from_spec(ispec)
        # registered so dataclasses/pickle introspection inside the
        # provider resolves its module while executing
        sys.modules[mod_name] = mod
        ispec.loader.exec_module(mod)
        return mod
    return importlib.import_module(entry)


def collect_programs(providers=DEFAULT_PROVIDERS) -> ProgramRegistry:
    """Import each provider and gather its declarations.  A provider
    without ``ir_programs`` is a loud error — a silently skipped
    provider would shrink the gate's coverage to whatever still
    declares."""
    reg = ProgramRegistry()
    for entry in providers:
        mod = _import_provider(entry)
        fn = getattr(mod, "ir_programs", None)
        if fn is None:
            raise ValueError(
                f"IR provider {entry!r} has no ir_programs(reg) — "
                f"remove it from the provider list or declare programs")
        fn(reg)
    return reg
