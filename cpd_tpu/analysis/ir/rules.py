"""The program-scope rules: machine-checked contracts over traced
jaxpr facts (trace.py) of the registered programs (registry.py).

Six rules, each with a fixture registry pinning its true positives
(tests/fixtures/analysis/ir_*_bad.py / tests/test_analysis_ir.py):

``ir-trace``        a registered program that fails to trace IS a
                    finding (and the CLI exits 2) — never a silent
                    skip; an unverifiable contract is a broken gate.
``ir-schedule``     the collective-schedule race/desync detector.
``ir-wire-ledger``  jaxpr-counted transport bytes == the analytic
                    tables.
``ir-bitwise``      no ulp-unstable primitive in a bitwise-gated
                    program.
``ir-overlap``      overlap-configured programs must actually
                    interleave.
``ir-retrace``      distinct programs in one StepTable family must
                    carry distinct cache keys.

This module imports no jax — rules consume plain extracted facts — so
registration at ``cpd_tpu.analysis`` import keeps the base package
stdlib-only.
"""

from __future__ import annotations

from typing import Iterator

from ..core import Finding, Rule, register
from .trace import TracedProgram, schedule_counter

__all__ = ["ProgramRule", "ProgramSet"]

# the ulp-unstable transcendental class: XLA lowers these to polynomial
# expansions whose final ulp DIFFERS BETWEEN COMPILED PROGRAMS (the
# PR 12 exp2/log2 bug class; pow shares the lowering).  exp/log/erf/
# tanh/rsqrt are deliberately absent from the default set: they are
# used by every softmax/normalizer and their cross-program stability is
# covered by the value-parity twin tests — a spec can still blacklist
# them per program via a stricter contract if a future backend breaks
# one.  Blessed exact replacements: aps.exp2_exact / _ceil_log2_exact /
# numerics._pow2 (bit assembly — no such primitive ever appears).
UNSTABLE_PRIMS = ("exp2", "log2", "pow")


class ProgramSet:
    """What a program rule checks: every TracedProgram of one run."""

    def __init__(self, programs: list):
        self.programs: list[TracedProgram] = list(programs)

    def ok(self) -> list:
        return [p for p in self.programs if p.ok]

    def groups(self, attr: str) -> dict:
        out: dict = {}
        for p in self.ok():
            key = getattr(p.spec, attr)
            if key is not None:
                out.setdefault(key, []).append(p)
        return out


class ProgramRule(Rule):
    """Base for program-scope rules: ``check`` receives a ProgramSet."""

    scope = "program"

    def check(self, programs: ProgramSet) -> Iterator[Finding]:
        raise NotImplementedError

    def _finding(self, spec, message: str) -> Finding:
        path, line = spec.origin
        return Finding(path=path, line=line, col=0, rule=self.id,
                       message=message)


@register
class TraceHonesty(ProgramRule):
    """A registered program that cannot be traced reports a finding —
    the analyzer refuses to pretend it verified a contract it never
    saw.  The CLI maps any ir-trace finding to exit 2 (analyzer-broke),
    not exit 1 (lint findings): the gate is DOWN, not clean."""

    id = "ir-trace"
    summary = ("registered program failed to trace — its contracts are "
               "unverified (exit 2, never a silent skip)")

    def check(self, programs: ProgramSet) -> Iterator[Finding]:
        for p in programs.programs:
            if not p.ok:
                yield self._finding(
                    p.spec, f"program {p.spec.name!r} failed to trace: "
                            f"{p.error}")


@register
class CollectiveSchedule(ProgramRule):
    """The distributed race/deadlock detector.  (1) Ladder-rung twins
    that claim bitwise parity (same ``twin`` group) must move the
    IDENTICAL multiset of transport collectives — kind, mesh axes,
    payload dtype/shape, trip count; a twin that gathers an extra
    tensor, rides a different axis or reshapes its wire has silently
    changed the reduction it claims to reproduce, and at pod scale a
    desynced schedule is a hang, not a wrong answer.  (2) No transport
    collective may sit under a ``lax.cond`` whose branches carry
    unequal collective sets: replicas disagreeing on the predicate
    would leave some ranks waiting at a rendezvous the others never
    enter — the MLPerf-pods divergent-program deadlock."""

    id = "ir-schedule"
    summary = ("collective schedule must be identical across bitwise "
               "twins; no collective under a divergent cond branch")

    def check(self, programs: ProgramSet) -> Iterator[Finding]:
        for p in programs.ok():
            for c in p.facts["cond_divergent"]:
                yield self._finding(
                    p.spec,
                    f"program {p.spec.name!r}: transport collectives "
                    f"differ across cond branches "
                    f"({c['branches']}) — a divergent predicate "
                    f"deadlocks the mesh")
        for group, members in sorted(programs.groups("twin").items()):
            if len(members) < 2:
                yield self._finding(
                    members[0].spec,
                    f"twin group {group!r} has a single traced member "
                    f"({members[0].spec.name!r}) — nothing to compare "
                    f"its schedule against")
                continue
            base = members[0]
            base_sched = schedule_counter(base.facts["collectives"])
            for other in members[1:]:
                sched = schedule_counter(other.facts["collectives"])
                if sched == base_sched:
                    continue
                extra = {k: v for k, v in sched.items()
                         if base_sched.get(k) != v}
                missing = {k: v for k, v in base_sched.items()
                           if sched.get(k) != v}
                yield self._finding(
                    other.spec,
                    f"twin group {group!r}: {other.spec.name!r} and "
                    f"{base.spec.name!r} claim bitwise parity but move "
                    f"different collective schedules — "
                    f"only in {other.spec.name!r}: "
                    f"{sorted(map(str, extra))}; only in "
                    f"{base.spec.name!r}: {sorted(map(str, missing))}")


@register
class WireLedger(ProgramRule):
    """jaxpr-counted transport payload bytes per device must EQUAL the
    analytic tables (`ring_transport_bytes` / `gather_transport_bytes`
    / `zero2_transport_bytes`, blocked sidecars included).  The
    analytics are what docs/PERF.md and the benches quote; a program
    quietly shipping more — an fp32 debug gather, an unpacked hop, a
    forgotten sidecar — fails lint instead of shipping a wire the
    ledger never priced."""

    id = "ir-wire-ledger"
    summary = ("counted collective wire bytes must equal the analytic "
               "transport tables (blocked sidecars included)")

    def check(self, programs: ProgramSet) -> Iterator[Finding]:
        for p in programs.ok():
            if p.spec.wire is None:
                continue
            if p.facts["unpriceable"]:
                yield self._finding(
                    p.spec,
                    f"program {p.spec.name!r}: wire contract declared "
                    f"but a transport collective is unpriceable "
                    f"(inside while/cond, or on an axis missing from "
                    f"axis_sizes)")
                continue
            try:
                expected = int(p.spec.wire())
            except Exception as e:  # noqa: BLE001 — surfaced, not raised
                yield self._finding(
                    p.spec, f"program {p.spec.name!r}: wire contract "
                            f"thunk crashed: {type(e).__name__}: {e}")
                continue
            got = int(p.facts["transport_bytes"])
            if got != expected:
                sched = sorted(map(str, schedule_counter(
                    p.facts["collectives"])))
                yield self._finding(
                    p.spec,
                    f"program {p.spec.name!r}: wire ledger mismatch — "
                    f"jaxpr moves {got} bytes/device, analytic table "
                    f"says {expected} (schedule: {sched})")


@register
class BitwiseStability(ProgramRule):
    """Programs registered as bitwise-gated must not contain an
    ulp-unstable transcendental primitive (exp2/log2/pow): XLA's
    polynomial lowerings land on different final ulps in different
    compiled programs, so any cross-program bitwise contract riding
    one holds only by luck — the PR 12 ``aps.exp2_exact`` bug class,
    found mechanically.  The blessed helpers (bit-assembly exp2_exact /
    _ceil_log2_exact / _pow2) emit no such primitive, so a hit always
    names real exposure.  A spec may bless a named primitive with a
    justification via ``allow_unstable``."""

    id = "ir-bitwise"
    summary = ("no ulp-unstable primitive (exp2/log2/pow) inside a "
               "bitwise-gated program outside the blessed exact helpers")

    def check(self, programs: ProgramSet) -> Iterator[Finding]:
        for p in programs.ok():
            if not p.spec.bitwise:
                continue
            allowed = {a.split()[0] for a in p.spec.allow_unstable}
            for prim in UNSTABLE_PRIMS:
                n = p.facts["prims"].get(prim, 0)
                if n and prim not in allowed:
                    yield self._finding(
                        p.spec,
                        f"program {p.spec.name!r} is bitwise-gated but "
                        f"contains {n} `{prim}` equation(s) — "
                        f"program-dependent final ulp (use the exact "
                        f"bit-assembly helpers: aps.exp2_exact / "
                        f"_ceil_log2_exact / numerics._pow2, or bless "
                        f"it via allow_unstable with a justification)")


@register
class OverlapInterleaving(ProgramRule):
    """`overlap_evidence` generalized into the registry: a program
    declared ``overlap=True`` must actually interleave — transport
    collectives emitted while matmul/conv compute is still pending in
    the jaxpr (the dependency freedom XLA needs to hide hops under
    backward compute); ``overlap=False`` must strictly postdate all
    compute (the monolith shape).  Structural, timing-free — a loaded
    CI box cannot flake it — and now gated for EVERY overlap-configured
    registered program, not just where a bench script happened to call
    the probe."""

    id = "ir-overlap"
    summary = ("overlap-configured programs must interleave transport "
               "with compute in the jaxpr (monoliths must not)")

    def check(self, programs: ProgramSet) -> Iterator[Finding]:
        for p in programs.ok():
            if p.spec.overlap is None:
                continue
            ev = p.facts["evidence"]
            if p.spec.overlap and not ev["interleaved"]:
                yield self._finding(
                    p.spec,
                    f"program {p.spec.name!r} is overlap-configured "
                    f"but its jaxpr is a monolith — every transport "
                    f"collective postdates all compute ({ev})")
            elif not p.spec.overlap and ev["interleaved"]:
                yield self._finding(
                    p.spec,
                    f"program {p.spec.name!r} is declared monolithic "
                    f"but its transport interleaves with compute "
                    f"({ev}) — the twin claim is measuring the wrong "
                    f"schedule")


@register
class RetraceCompleteness(ProgramRule):
    """The retrace-completeness probe, the PR 5 half-keyed StepTable
    bug verified DYNAMICALLY: members of one ``retrace_group`` are the
    entries one jit/StepTable cache family would hold, traced at
    perturbed config coordinates.  Two members whose traced programs
    DIFFER (jaxpr fingerprints) while their declared cache keys are
    EQUAL would be served each other's compiled step after a ladder
    transition — a key coordinate is missing.  (Distinct keys for
    identical programs are fine: over-keying only costs a retrace.)"""

    id = "ir-retrace"
    summary = ("distinct traced programs in one cache-key family must "
               "carry distinct ladder_step_keys")

    def check(self, programs: ProgramSet) -> Iterator[Finding]:
        for group, members in sorted(
                programs.groups("retrace_group").items()):
            by_key: dict = {}
            for p in members:
                by_key.setdefault(repr(p.spec.retrace_key),
                                  []).append(p)
            for key, ps in sorted(by_key.items()):
                fps = {p.facts["jaxpr_sha1"] for p in ps}
                if len(fps) > 1:
                    names = sorted(p.spec.name for p in ps)
                    yield self._finding(
                        ps[0].spec,
                        f"cache-key family {group!r}: programs {names} "
                        f"trace to {len(fps)} DISTINCT jaxprs but share "
                        f"the cache key {key} — a config coordinate is "
                        f"missing from ladder_step_key (the PR 5 "
                        f"half-keyed StepTable bug)")
