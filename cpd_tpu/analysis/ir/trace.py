"""Abstract tracing + fact extraction for the program-contract rules.

``trace_program`` traces one `ProgramSpec` to its jaxpr with
``jax.make_jaxpr`` (abstract — no compile, no execute) and distills the
serializable **facts** the rules consume:

* ``collectives`` — the transport-collective schedule: one entry per
  ``ppermute``/``all_gather``/``all_to_all`` equation with its axis
  names, payload ``(shape, dtype)`` list and its TRIP COUNT (a
  collective inside a ``lax.scan`` body executes ``length`` times per
  enclosing trip; nested scans multiply).  ``psum`` is deliberately not
  transport — scalar bookkeeping and forward tensor-parallel reductions
  would otherwise read as gradient wire (same doctrine as
  `overlap.overlap_evidence`).
* ``transport_bytes`` — per-device bytes the schedule puts on the wire:
  a ppermute sends its payload once per trip; an all_gather sends its
  (local) payload to W-1 peers; an all_to_all of a leading-axis-W array
  keeps 1/W local and sends the rest.  W comes from the spec's
  ``axis_sizes``.  A transport collective under a ``while`` (unknown
  trip count) or on an undeclared axis flips ``unpriceable`` — the
  ledger rule reports it rather than guessing.
* ``prims`` — primitive census with trip-count multiplicity (the
  bitwise-stability rule's input).
* ``evidence`` — `overlap.evidence_from_prims` over the emission-order
  stream: the ONE interleaving implementation, shared with
  `overlap_evidence`.
* ``cond_divergent`` — ``cond`` equations whose branches carry UNEQUAL
  transport-collective multisets: the classic distributed deadlock/race
  shape (some replicas enter the collective, others never arrive).
* ``jaxpr_sha1`` — fingerprint of the printed jaxpr, the retrace
  probe's program identity.

All facts are plain JSON-serializable data, so the program cache
(run.py) can serve them without re-importing jax.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from .registry import ProgramSpec

__all__ = ["TracedProgram", "trace_program", "TRANSPORT_PRIMS",
           "schedule_counter"]

# must stay equal to overlap._COLLECTIVE_PRIMS (asserted in tests): one
# definition of "transport collective" across the evidence probe and
# the IR rules
TRANSPORT_PRIMS = ("ppermute", "all_gather", "all_to_all")


class TracedProgram:
    """One program's extracted facts (or its trace failure)."""

    def __init__(self, spec: ProgramSpec, facts: Optional[dict] = None,
                 error: Optional[str] = None):
        self.spec = spec
        self.facts = facts
        self.error = error

    @property
    def ok(self) -> bool:
        return self.error is None


def _iter_jaxprs(v):
    import jax.core as jc
    if isinstance(v, jc.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, jc.Jaxpr):
        yield v
    elif isinstance(v, (tuple, list)):
        for w in v:
            yield from _iter_jaxprs(w)


def _aval_info(v):
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return None
    import numpy as np
    shape = tuple(int(s) for s in aval.shape)
    return (shape, str(aval.dtype),
            int(np.prod(shape)) if shape else 1,
            int(aval.dtype.itemsize))


def _axis_names(params) -> tuple:
    ax = params.get("axis_name", params.get("axes"))
    if ax is None:
        return ()
    if isinstance(ax, (tuple, list)):
        return tuple(str(a) for a in ax)
    return (str(ax),)


def _walk(jaxpr, mult: int, in_while: bool, in_cond: bool, state: dict):
    """Emission-order walk (the traversal `overlap._walk_eqns` uses),
    carrying the scan trip multiplier and inside-while/-cond flags."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        infos = [i for i in map(_aval_info, eqn.invars) if i is not None]
        max_elems = max((i[2] for i in infos), default=0)
        state["stream"].append((name, max_elems))
        state["prims"][name] = state["prims"].get(name, 0) + mult
        if name in TRANSPORT_PRIMS:
            state["collectives"].append({
                "kind": name,
                "axes": list(_axis_names(eqn.params)),
                "payload": [[list(i[0]), i[1]] for i in infos],
                "bytes": sum(i[2] * i[3] for i in infos),
                "mult": mult,
                "in_while": in_while,
                "in_cond": in_cond,
            })
        if name == "cond":
            branches = []
            for br in eqn.params.get("branches", ()):
                sub = {"stream": [], "prims": {}, "collectives": [],
                       "conds": []}
                for j in _iter_jaxprs(br):
                    _walk(j, 1, in_while, True, sub)
                branches.append(sub["collectives"])
            counters = [schedule_counter(b) for b in branches]
            if any(c != counters[0] for c in counters[1:]):
                state["conds"].append({
                    "branches": [sorted(str(k) for k in c) for c in
                                 counters]})
            # the generic params walk below ALSO descends into the
            # branches for the main census/evidence; their collectives
            # carry in_cond=True, which the byte ledger refuses to
            # price (only one branch runs — counting both would lie)
        inner_mult = mult
        inner_while = in_while
        inner_cond = in_cond or name == "cond"
        if name == "scan":
            inner_mult = mult * int(eqn.params.get("length", 1))
        elif name == "while":
            inner_while = True
        for v in eqn.params.values():
            for j in _iter_jaxprs(v):
                _walk(j, inner_mult, inner_while, inner_cond, state)


def schedule_counter(collectives) -> dict:
    """The schedule multiset: ``(kind, axes, payload) -> total trips``.
    Trip-count aggregation makes a scanned hop loop and its unrolled
    twin compare equal — the wire they move is identical."""
    out: dict = {}
    for c in collectives:
        key = (c["kind"], tuple(c["axes"]),
               tuple((tuple(s), d) for s, d in
                     (tuple(p) for p in c["payload"])))
        out[key] = out.get(key, 0) + c["mult"]
    return out


def _transport_bytes(collectives, axis_sizes) -> tuple:
    """(per-device bytes, unpriceable?) for the extracted schedule."""
    total = 0
    unpriceable = False
    for c in collectives:
        if c["in_while"] or c.get("in_cond"):
            unpriceable = True
            continue
        w = 1
        known = True
        for a in c["axes"]:
            if not axis_sizes or a not in axis_sizes:
                known = False
                break
            w *= int(axis_sizes[a])
        if not known:
            unpriceable = True
            continue
        b = c["bytes"]
        if c["kind"] == "ppermute":
            sent = b
        elif c["kind"] == "all_gather":
            sent = b * (w - 1)
        else:                               # all_to_all
            sent = (b // w) * (w - 1) if w else 0
        total += sent * c["mult"]
    return total, unpriceable


def trace_program(spec: ProgramSpec) -> TracedProgram:
    """Trace one spec abstractly and extract its facts; any failure —
    build error, trace error, too few devices — is captured as the
    TracedProgram's ``error``, never raised (the ir-trace rule turns it
    into a finding; a silent skip is the one outcome forbidden)."""
    try:
        import jax
        from ..ir import registry as _reg
        if len(jax.devices()) < _reg.IR_WORLD:
            raise RuntimeError(
                f"IR tracing needs {_reg.IR_WORLD} virtual CPU devices, "
                f"have {len(jax.devices())} — jax was initialized "
                f"before ensure_cpu_devices() could size the platform")
        fn, args = spec.build()
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 — every failure is a finding
        return TracedProgram(
            spec, error=f"{type(e).__name__}: {e}")
    state: dict = {"stream": [], "prims": {}, "collectives": [],
                   "conds": []}
    _walk(closed.jaxpr, 1, False, False, state)
    from cpd_tpu.parallel.overlap import evidence_from_prims
    evidence = evidence_from_prims(state["stream"])
    bytes_counted, unpriceable = _transport_bytes(
        state["collectives"], spec.axis_sizes)
    facts = {
        "name": spec.name,
        "collectives": state["collectives"],
        "transport_bytes": bytes_counted,
        "unpriceable": unpriceable,
        "prims": state["prims"],
        "evidence": evidence,
        "cond_divergent": state["conds"],
        "jaxpr_sha1": hashlib.sha1(
            str(closed.jaxpr).encode()).hexdigest(),
        "n_eqns": len(state["stream"]),
    }
    return TracedProgram(spec, facts=facts)
