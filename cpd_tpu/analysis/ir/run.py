"""The IR pass driver: collect → (cached) trace → rules → findings.

Mirrors engine.run_analysis's shape for the program scope.  Facts —
not findings — are what the cache holds: the schedule rule compares
twins ACROSS programs, so a rule needs every member's facts even when
only one re-traced; rules re-run every time (they are dict lookups),
tracing is what the cache saves.  A program's fingerprint covers

    (IR schema, jax version, spec dep files' (mtime_ns, size))

where the dep set is the spec's declared modules PLUS the provider
module that declared it — editing any of them re-traces exactly the
affected programs; a warm run over an unchanged tree re-traces ZERO
(pinned by tests/test_analysis_ir.py).  The resolved lint config is
folded in by the caller through ``extra_fingerprint`` (engine.py), the
same invalidate-on-config-edit contract the file cache carries.
"""

from __future__ import annotations

import dataclasses
import hashlib
import importlib
import json
import os
from typing import Iterable, Optional

from ..cache import DEFAULT_CACHE_DIR
from ..core import Finding
from .registry import (DEFAULT_PROVIDERS, ProgramSpec, collect_programs,
                       ensure_cpu_devices)
from .rules import ProgramSet
from .trace import TracedProgram, trace_program

__all__ = ["IRResult", "run_ir", "IR_SCHEMA_VERSION"]

# bump whenever trace.py's fact extraction changes shape
IR_SCHEMA_VERSION = 1


@dataclasses.dataclass
class IRResult:
    findings: list
    programs_checked: int
    programs_traced: int     # cache misses; 0 on a warm unchanged tree
    trace_failures: int      # nonzero maps to CLI exit 2


def _dep_files(spec: ProgramSpec, provider_file: Optional[str]) -> list:
    paths = []
    if provider_file:
        paths.append(provider_file)
    for dep in spec.deps:
        try:
            mod = importlib.import_module(dep)
            f = getattr(mod, "__file__", None)
        except Exception:   # noqa: BLE001 — a missing dep is a stale key
            f = None
        if f:
            paths.append(f)
    return sorted(set(os.path.abspath(p) for p in paths))


def _fingerprint(spec: ProgramSpec, provider_file: Optional[str],
                 extra: str) -> Optional[str]:
    import jax
    parts = [IR_SCHEMA_VERSION, jax.__version__, extra, spec.name]
    for path in _dep_files(spec, provider_file):
        try:
            st = os.stat(path)
        except OSError:
            return None
        parts.append([path, st.st_mtime_ns, st.st_size])
    return hashlib.sha1(json.dumps(parts).encode()).hexdigest()


class _FactCache:
    """One JSON file per program under ``<cache_dir>/ir/``.  Corrupt or
    stale entries are misses, never errors (accelerator, not truth).
    Trace FAILURES are never cached: a failure can be environmental
    (device count, a flaky import) and must re-verify every run."""

    def __init__(self, directory: str):
        self.directory = os.path.join(directory, "ir")

    def _path(self, name: str) -> str:
        key = hashlib.sha1(name.encode()).hexdigest()
        return os.path.join(self.directory, key + ".json")

    def get(self, name: str, fingerprint: Optional[str]):
        if fingerprint is None:
            return None
        try:
            with open(self._path(name), encoding="utf-8") as fh:
                entry = json.load(fh)
        except (OSError, ValueError):
            return None
        if entry.get("fingerprint") != fingerprint:
            return None
        facts = entry.get("facts")
        return facts if isinstance(facts, dict) else None

    def put(self, name: str, fingerprint: Optional[str],
            facts: dict) -> None:
        if fingerprint is None:
            return
        try:
            os.makedirs(self.directory, exist_ok=True)
            tmp = self._path(name) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"fingerprint": fingerprint, "facts": facts},
                          fh)
            os.replace(tmp, self._path(name))
        except OSError:
            pass    # read-only checkout still lints


def run_ir(select: Optional[Iterable[str]] = None,
           providers=DEFAULT_PROVIDERS,
           use_cache: bool = True,
           cache_dir: Optional[str] = None,
           extra_fingerprint: str = "") -> IRResult:
    """Run the program-contract pass (module docstring).

    ``select`` filters RULES (not programs) exactly like the file pass;
    ``providers`` overrides the registry source (fixture registries in
    tests pass .py paths); ``extra_fingerprint`` folds caller context —
    the resolved config — into every program's cache key."""
    from ..core import LintError, run_program_rules_on
    ensure_cpu_devices()
    try:
        registry = collect_programs(providers)
    except Exception as e:  # noqa: BLE001 — surfaced as exit 2
        raise LintError(f"IR program collection failed: "
                        f"{type(e).__name__}: {e}") from e
    cache = _FactCache(cache_dir or DEFAULT_CACHE_DIR) if use_cache \
        else None
    programs: list[TracedProgram] = []
    traced = 0
    for spec in registry.specs:
        provider_file = spec.origin[0] if spec.origin else None
        fp = None
        if cache is not None:
            fp = _fingerprint(spec, provider_file, extra_fingerprint)
            facts = cache.get(spec.name, fp)
            if facts is not None:
                programs.append(TracedProgram(spec, facts=facts))
                continue
        tp = trace_program(spec)
        traced += 1
        if tp.ok and cache is not None:
            cache.put(spec.name, fp, tp.facts)
        programs.append(tp)
    progset = ProgramSet(programs)
    findings: list[Finding] = run_program_rules_on(progset, select=select)
    failures = sum(1 for p in programs if not p.ok)
    return IRResult(findings=sorted(findings),
                    programs_checked=len(programs),
                    programs_traced=traced,
                    trace_failures=failures)
