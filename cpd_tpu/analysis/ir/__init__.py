"""analysis v3 — jaxpr-level program-contract verification.

The per-file (module) and whole-program (project) rule scopes see Python
AST; neither can see the invariants CPD actually sells — the compiled
program's collective schedule, its packed wire bytes, its ulp-stability,
its overlap interleaving.  The PR 12 ``exp2_exact`` fix is the canonical
miss: XLA:CPU's ``exp2`` is off by an ulp for most integer inputs and
*program-dependent*, so every cross-program bitwise APS contract held by
luck — and no AST rule could have said so.  This package adds the third
rule scope, ``program``: subsystems *declare* their contract-bearing
programs in a registry (`registry.ProgramRegistry`; declarations live in
``parallel/ring.py``, ``parallel/zero.py``, ``parallel/overlap.py``,
``parallel/reduction.py``, ``train/step.py``, ``serve/model.py``), the
tracer (`trace.py`) traces each one ABSTRACTLY on CPU to its jaxpr
(``jax.make_jaxpr`` over ``ShapeDtypeStruct`` inputs — no compile, no
execute, no weights) and extracts serializable **facts** (collective
schedule with scan trip counts, transport bytes, primitive census,
interleaving evidence, cond-branch collective sets, a jaxpr
fingerprint), and the program rules (`rules.py`) machine-check the
declared contracts against those facts.  Findings ride the existing
engine/config/SARIF/CLI machinery and anchor at the declaration site.

Unlike the rest of the analysis package this scope needs jax — it is
therefore OFF by default (``python -m cpd_tpu.analysis`` stays
stdlib-only and milliseconds) and runs only under the CLI's ``--ir``
flag / ``run_analysis(ir=True)`` — the CI ``ir-contracts`` gate.  Traced
facts are fingerprint-cached per program over the program's declared
source deps (`run.py`), so a warm run re-traces zero unchanged programs.

The rule classes themselves import no jax and register with the normal
registry at package import, so ``--list-rules``/``--explain``/config
exemptions cover them everywhere.
"""

from .registry import (ProgramRegistry, ProgramSpec, collect_programs,
                       DEFAULT_PROVIDERS, ensure_cpu_devices)
from .rules import ProgramRule, ProgramSet
from .run import IRResult, run_ir

__all__ = ["ProgramRegistry", "ProgramSpec", "collect_programs",
           "DEFAULT_PROVIDERS", "ensure_cpu_devices", "ProgramRule",
           "ProgramSet", "IRResult", "run_ir"]
