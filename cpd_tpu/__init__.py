"""cpd_tpu — TPU-native customized-precision distributed training.

A JAX/XLA/Pallas re-design of the CPD emulator (reference:
CPDtorch/quant/__init__.py:4-5, CPDtorch/utils/dist_util.py): train with
arbitrary eXmY floating-point formats — casts, quantized-accumulator
GEMM, low-precision gradient all-reduce with APS and Kahan compensation —
over jax.sharding meshes instead of NCCL process groups.

The reference's ``import CPDtorch`` surface (float_quantize, quantizer,
Quantizer, quant_gemm, Quant_Linear → QuantLinear, Quant_Conv →
QuantConv, plus dist_util's dist_init / sum_gradients / broadcast) is
re-exported here at the package root.  Attribute access is lazy (PEP
562) so ``import cpd_tpu`` stays cheap — jax/flax load only when the
API is first touched.
"""

from __future__ import annotations

__version__ = "0.2.0"

# name -> submodule providing it
_EXPORTS = {
    # L1 quant API (reference CPDtorch/quant/__init__.py:4-5)
    "float_quantize": "quant",
    "quantizer": "quant",
    "quant_gemm": "quant",
    "qgemm": "quant",   # (exp, man)-consistent spelling (ISSUE 15)
    "Quantizer": "quant",
    "QuantLinear": "quant",
    "QuantConv": "quant",
    "cast_to_format": "quant",
    # L2 distributed layer (reference CPDtorch/utils/dist_util.py)
    "dist_init": "parallel",
    "sum_gradients": "parallel",
    "broadcast_from": "parallel",
    "replicate": "parallel",
    "make_mesh": "parallel",
    "make_sum_gradients_fn": "parallel",
    "emulate_node_reduce": "parallel",
}

__all__ = ["__version__", *_EXPORTS]


def __getattr__(name: str):
    submodule = _EXPORTS.get(name)
    if submodule is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(f".{submodule}", __name__)
    value = getattr(mod, name)
    globals()[name] = value  # cache for subsequent lookups
    return value


def __dir__():
    return sorted(__all__)
