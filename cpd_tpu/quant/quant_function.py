"""Custom-precision quantization API (L1 of the layer map).

TPU-native re-implementation of the reference Python quant API
(reference: CPDtorch/quant/quant_function.py).  Differences by design:

* Pure functional — `float_quantize` returns a new array; the reference
  mutates contiguous CUDA inputs in place (quant.cu:22-23).  Numerics are
  identical.
* `quantizer` is a `jax.custom_vjp` identity instead of a torch autograd
  Function (quant_function.py:33-57), with the same (8,23) shortcut.
* `quant_gemm` (quant_function.py:78-98) supports two modes:
  - ``faithful`` (default, matching the CUDA `tvm_gemm` kernel,
    float_kernel.cu:103-220): sequential K-loop where every multiply and
    every Kahan-compensated accumulation step is re-cast to eXmY.  On TPU
    this runs as a `lax.scan` of rank-1 updates on the VPU — the MXU cannot
    requantize mid-dot, the same fidelity/throughput trade the reference
    made by not using tensor cores.
  - ``fast``: fp32 MXU dot followed by a single output cast — the
    "deployment" path for when emulation of the accumulator is not needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .numerics import (HEALTH_FIELDS, cast_to_format, cast_to_format_sr,
                       quant_health)

__all__ = ["float_quantize", "quantizer", "quantizer_sr", "quant_gemm",
           "qgemm", "qgemm_stats",
           "float_quantize_stats", "quant_gemm_stats", "quantizer_stats",
           "tree_quant_health", "HEALTH_FIELDS"]


def _site_key(key_data, site: int):
    """Rebuild a PRNG key from raw uint32 key data and fold in a cast-site
    index — the one shared key-derivation recipe for every custom_vjp
    SR consumer (quantizer_sr here; quant_linear_fn in quant_module)."""
    return jax.random.fold_in(jax.random.wrap_key_data(key_data), site)


def _validate_rounding(rounding: str, key) -> bool:
    """Shared rounding/key argument contract; returns True for SR."""
    if rounding == "nearest":
        if key is not None:
            raise ValueError("a PRNG key was passed but rounding='nearest' "
                             "would ignore it; did you mean "
                             "rounding='stochastic'?")
        return False
    if rounding == "stochastic":
        if key is None:
            raise ValueError("rounding='stochastic' requires a PRNG key")
        return True
    raise ValueError(f"unknown rounding mode: {rounding!r}")


def float_quantize(x: jnp.ndarray, exp: int, man: int,
                   rounding: str = "nearest", key=None) -> jnp.ndarray:
    """Quantize an FP32 array into the eXmY format.

    Mirrors reference `float_quantize` (quant_function.py:60-75); argument
    order (exp, man) preserved.  Works on any shape, any backend (the
    reference raises NotImplementedError on CPU, quant_function.py:28-29 —
    here XLA compiles the same code for CPU/TPU).

    `rounding` selects the significand rounding:
    - ``"nearest"`` (default): round-to-nearest-even, bit-exact to the
      reference CUDA kernel.
    - ``"stochastic"`` (beyond-reference): unbiased stochastic rounding
      driven by the required PRNG `key` — the standard companion to RTNE
      for low-precision weight updates (avoids update stagnation when
      |update| < ulp/2).  All non-rounding semantics are identical.
    """
    if _validate_rounding(rounding, key):
        return cast_to_format_sr(x, exp, man, key)
    return cast_to_format(x, exp, man)


def float_quantize_stats(x: jnp.ndarray, exp: int, man: int,
                         rounding: str = "nearest", key=None) -> tuple:
    """`float_quantize` plus its numeric-health counters.

    Returns ``(q, health)`` where ``q`` is BITWISE identical to
    ``float_quantize(x, exp, man, rounding, key)`` — telemetry observes
    the cast's (input, output) pair, it never touches the cast itself
    (gated in tools/bench_reduce.py --smoke across formats × rounding) —
    and ``health`` is `numerics.quant_health`'s {sat, underflow, nan,
    total} float32 scalars (the precision supervisor's sensor,
    resilience/precision.py)."""
    q = float_quantize(x, exp, man, rounding=rounding, key=key)
    return q, quant_health(x, q)


def tree_quant_health(before: jnp.ndarray, after) -> dict:
    """Summed `quant_health` over two matching pytrees (cast inputs and
    outputs, leaf for leaf).  Empty trees report all-zero counters."""
    out = {f: jnp.zeros([], jnp.float32) for f in HEALTH_FIELDS}
    for b, a in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        h = quant_health(b, a)
        out = {f: out[f] + h[f] for f in HEALTH_FIELDS}
    return out


def _health_vec(x, q) -> jnp.ndarray:
    """quant_health as a float32 (4,) vector in HEALTH_FIELDS order —
    the form that can ride autodiff cotangents (quantizer_stats)."""
    h = quant_health(x, q)
    return jnp.stack([h[f].astype(jnp.float32) for f in HEALTH_FIELDS])


def quantizer(forward_exp: int = 8, forward_man: int = 23,
              backward_exp: int = 8, backward_man: int = 23):
    """Factory returning a function that quantizes activations on the forward
    pass and cotangents on the backward pass, with identity shortcuts when
    the format is (8, 23) — reference quant_function.py:33-57."""

    @jax.custom_vjp
    def _round(x):
        if forward_exp == 8 and forward_man == 23:
            return x
        return cast_to_format(x, forward_exp, forward_man)

    def _round_fwd(x):
        return _round(x), None

    def _round_bwd(_, g):
        if backward_exp == 8 and backward_man == 23:
            return (g,)
        return (cast_to_format(g, backward_exp, backward_man),)

    _round.defvjp(_round_fwd, _round_bwd)
    return _round


def quantizer_sr(forward_exp: int = 8, forward_man: int = 23,
                 backward_exp: int = 8, backward_man: int = 23):
    """Stochastic-rounding `quantizer` (beyond-reference): returns
    ``fn(x, key_data)`` where `key_data` is raw uint32 PRNG key data
    (`jax.random.key_data`) — activations SR-cast on forward (site 0),
    cotangents on backward (site 1), independent subkeys.  The (8, 23)
    shortcuts match `quantizer` (SR at fp32 is the identity anyway)."""

    @jax.custom_vjp
    def _round(x, key_data):
        if forward_exp == 8 and forward_man == 23:
            return x
        return cast_to_format_sr(x, forward_exp, forward_man,
                                 _site_key(key_data, 0))

    def _round_fwd(x, key_data):
        return _round(x, key_data), key_data

    def _round_bwd(key_data, g):
        if backward_exp == 8 and backward_man == 23:
            return (g, None)
        return (cast_to_format_sr(g, backward_exp, backward_man,
                                  _site_key(key_data, 1)), None)

    _round.defvjp(_round_fwd, _round_bwd)
    return _round


def quantizer_stats(forward_exp: int = 8, forward_man: int = 23,
                    backward_exp: int = 8, backward_man: int = 23):
    """Stats-counting `quantizer`: both cast sites observed, neither
    changed.

    Returns ``fn(x, tap)`` where ``tap`` is a float32 (4,) zeros array.
    Forward: ``fn`` returns ``(y, fwd_health)`` with ``y`` bitwise
    identical to `quantizer`'s output and ``fwd_health`` the float32
    [sat, underflow, nan, total] vector (HEALTH_FIELDS order) of the
    forward activation cast.  Backward: a VJP cannot emit primal
    outputs, so the *backward* cast's health rides the one channel
    autodiff provides — the cotangent returned for the otherwise-unused
    ``tap`` input:

        (y, fwd_h), vjp = jax.vjp(fn, x, jnp.zeros(4))
        gx, bwd_h = vjp((g, jnp.zeros(4)))

    ``gx`` is bitwise identical to `quantizer`'s backward cast of ``g``;
    ``bwd_h`` is its health vector.  The (8, 23) shortcuts keep identity
    semantics on either side and report a counted no-op (sat/underflow
    only from values already Inf/0 in the data)."""

    @jax.custom_vjp
    def _round(x, tap):
        if forward_exp == 8 and forward_man == 23:
            q = x
        else:
            q = cast_to_format(x, forward_exp, forward_man)
        return q, _health_vec(x, q)

    def _round_fwd(x, tap):
        return _round(x, tap), None

    def _round_bwd(_, cot):
        g, _unused_health_cot = cot
        if backward_exp == 8 and backward_man == 23:
            gq = g
        else:
            gq = cast_to_format(g, backward_exp, backward_man)
        return gq, _health_vec(g, gq)

    _round.defvjp(_round_fwd, _round_bwd)
    return _round


def _quant_gemm_impl(a: jnp.ndarray, b: jnp.ndarray, man: int, exp: int,
                     mode: str, rounding: str, key, with_stats: bool):
    """Shared gemm body of `quant_gemm` / `quant_gemm_stats`.  With
    `with_stats` the five per-K-step accumulator casts (or the fast
    mode's output cast) are additionally observed by `quant_health` —
    same ops, same order, bitwise-identical product; the counters ride
    the scan carry as one float32 (4,) vector."""
    if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
        raise ValueError(f"quant_gemm expects (M,K)x(K,N); got {a.shape} x {b.shape}")
    sr = _validate_rounding(rounding, key)
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)

    def health_dict(vec):
        return {f: vec[i] for i, f in enumerate(HEALTH_FIELDS)}

    if mode == "fast":
        # True fp32 MXU dot (HIGHEST forces fp32 multiply passes on TPU,
        # where the default would be bf16) followed by one output cast.
        out = jnp.dot(a, b, precision=lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)
        if exp == 8 and man == 23:
            if with_stats:        # no cast ran: a counted no-op
                return out, {f: jnp.zeros([], jnp.float32)
                             for f in HEALTH_FIELDS}
            return out
        if sr:
            cast = cast_to_format_sr(out, exp, man, key)
        else:
            cast = cast_to_format(out, exp, man)
        if with_stats:
            return cast, quant_health(out, cast)
        return cast
    if mode != "faithful":
        raise ValueError(f"unknown quant_gemm mode: {mode!r}")
    # NOTE: no (8,23) shortcut here — the reference CUDA kernel runs the
    # Kahan-compensated sequential loop for every format including fp32
    # (quant_function.py:78-98 has no shortcut), and cast_to_format(8,23)
    # still flushes fp32-subnormal intermediates, so bit-parity requires
    # the full scan.  Use mode="fast" when emulation is not needed.

    M, K = a.shape
    N = b.shape[1]

    def step(carry, ab_k):
        s, c, cnt = carry
        a_k, b_k, i = ab_k  # (M,), (N,), scalar k index
        healths = []
        if sr:
            kk = jax.random.fold_in(key, i)  # one hash per K step

            def q(t, site):
                out = cast_to_format_sr(t, exp, man,
                                        jax.random.fold_in(kk, site))
                if with_stats:
                    healths.append(_health_vec(t, out))
                return out
        else:
            def q(t, site):
                out = cast_to_format(t, exp, man)
                if with_stats:
                    healths.append(_health_vec(t, out))
                return out
        tmp = q(a_k[:, None] * b_k[None, :], 0)
        y = q(tmp - c, 1)
        t = q(s + y, 2)
        c = q(q(t - s, 3) - y, 4)
        if with_stats:
            cnt = cnt + sum(healths)
        return (t, c, cnt), None

    init = (jnp.zeros((M, N), jnp.float32), jnp.zeros((M, N), jnp.float32),
            jnp.zeros((len(HEALTH_FIELDS),), jnp.float32))
    (s, _, cnt), _ = lax.scan(step, init, (a.T, b, jnp.arange(K)))
    if with_stats:
        return s, health_dict(cnt)
    return s


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def qgemm(a: jnp.ndarray, b: jnp.ndarray, exp: int = 8, man: int = 23,
          mode: str = "faithful", rounding: str = "nearest",
          key=None) -> jnp.ndarray:
    """`quant_gemm` with the repo-consistent ``(exp, man)`` argument
    order — the canonical spelling (ISSUE 15 satellite).

    `quant_gemm` keeps the reference's positional ``(man, exp)`` order
    (quant_function.py:78-98) and stays as the back-compat shim; every
    OTHER format API in the repo takes ``(exp, man)``, which made the
    original order a positional-call footgun the format-bounds /
    format-flow lint rules had to special-case.  New code calls
    ``qgemm(a, b, exp=..., man=...)``; in-repo call sites are migrated.
    Numerics, modes, rounding and the stats twin (`qgemm_stats`) are
    identical — one `_quant_gemm_impl` body serves all four entries."""
    return _quant_gemm_impl(a, b, man, exp, mode, rounding, key, False)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def qgemm_stats(a: jnp.ndarray, b: jnp.ndarray, exp: int = 8,
                man: int = 23, mode: str = "faithful",
                rounding: str = "nearest", key=None) -> tuple:
    """`quant_gemm_stats` in the ``(exp, man)`` order — see `qgemm`."""
    return _quant_gemm_impl(a, b, man, exp, mode, rounding, key, True)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def quant_gemm(a: jnp.ndarray, b: jnp.ndarray, man: int = 23, exp: int = 8,
               mode: str = "faithful", rounding: str = "nearest",
               key=None) -> jnp.ndarray:
    """GEMM ``a @ b`` with an eXmY accumulator.

    BACK-COMPAT SHIM: the positional order here is the reference's
    ``(man, exp)`` — every other format API takes ``(exp, man)``.
    Prefer `qgemm` (same numerics, consistent order); this surface
    stays for reference parity and external callers, and the analyzer
    keeps its name-crossed table entry for exactly this signature.

    a: (M, K), b: (K, N) — reference quant_function.py:78-98.  The faithful
    mode reproduces the CUDA kernel's numerics exactly (float_kernel.cu:
    174-205): for k = 0..K-1 in order, with Kahan compensation, every
    intermediate re-cast to eXmY:

        tmp = cast(a[:, k] * b[k, :])
        y   = cast(tmp - c)
        t   = cast(s + y)
        c   = cast(cast(t - s) - y)
        s   = t

    The CUDA kernel's K-tiling (rx_outer/rx_inner) visits k strictly in
    ascending order, so a flat ordered scan is bit-identical.  Note the
    reference edge-path bug (uninitialized Kahan residual for the last row
    block when M % 16 != 0, float_kernel.cu:113,298) is UB, not semantics —
    we use a zero-initialized residual everywhere, which is what the main
    path does (float_kernel.cu:120).

    rounding="stochastic" (beyond-reference, requires `key`) replaces
    every cast — the five per-K-step faithful intermediates, or the fast
    mode's output cast — with the unbiased SR cast (one independent
    bitstream per (k, site)): the accumulator analog of the SR gradient
    pipeline, for emulating stochastic-rounding accumulators.
    """
    return _quant_gemm_impl(a, b, man, exp, mode, rounding, key, False)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def quant_gemm_stats(a: jnp.ndarray, b: jnp.ndarray, man: int = 23,
                     exp: int = 8, mode: str = "faithful",
                     rounding: str = "nearest", key=None) -> tuple:
    """`quant_gemm` plus accumulator health: ``(out, health)``.

    ``out`` is BITWISE identical to ``quant_gemm(...)`` (the stats ride
    the scan carry without touching the accumulation); ``health`` sums
    `quant_health` over EVERY cast the mode performs — faithful: all
    five per-K-step intermediates (total = 5·K·M·N), fast: the single
    output cast (zero counters at the (8,23) no-cast shortcut;
    float32 — a faithful GEMM's 5·K·M·N total would wrap int32).  A
    rising ``sat`` here means the accumulator format can no longer hold
    the running dot products — the GEMM-site feed of the precision
    supervisor's escalation ladder (resilience/precision.py)."""
    return _quant_gemm_impl(a, b, man, exp, mode, rounding, key, True)
