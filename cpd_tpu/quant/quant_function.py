"""Custom-precision quantization API (L1 of the layer map).

TPU-native re-implementation of the reference Python quant API
(reference: CPDtorch/quant/quant_function.py).  Differences by design:

* Pure functional — `float_quantize` returns a new array; the reference
  mutates contiguous CUDA inputs in place (quant.cu:22-23).  Numerics are
  identical.
* `quantizer` is a `jax.custom_vjp` identity instead of a torch autograd
  Function (quant_function.py:33-57), with the same (8,23) shortcut.
* `quant_gemm` (quant_function.py:78-98) supports two modes:
  - ``faithful`` (default, matching the CUDA `tvm_gemm` kernel,
    float_kernel.cu:103-220): sequential K-loop where every multiply and
    every Kahan-compensated accumulation step is re-cast to eXmY.  On TPU
    this runs as a `lax.scan` of rank-1 updates on the VPU — the MXU cannot
    requantize mid-dot, the same fidelity/throughput trade the reference
    made by not using tensor cores.
  - ``fast``: fp32 MXU dot followed by a single output cast — the
    "deployment" path for when emulation of the accumulator is not needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .numerics import cast_to_format, cast_to_format_sr

__all__ = ["float_quantize", "quantizer", "quantizer_sr", "quant_gemm"]


def _site_key(key_data, site: int):
    """Rebuild a PRNG key from raw uint32 key data and fold in a cast-site
    index — the one shared key-derivation recipe for every custom_vjp
    SR consumer (quantizer_sr here; quant_linear_fn in quant_module)."""
    return jax.random.fold_in(jax.random.wrap_key_data(key_data), site)


def _validate_rounding(rounding: str, key) -> bool:
    """Shared rounding/key argument contract; returns True for SR."""
    if rounding == "nearest":
        if key is not None:
            raise ValueError("a PRNG key was passed but rounding='nearest' "
                             "would ignore it; did you mean "
                             "rounding='stochastic'?")
        return False
    if rounding == "stochastic":
        if key is None:
            raise ValueError("rounding='stochastic' requires a PRNG key")
        return True
    raise ValueError(f"unknown rounding mode: {rounding!r}")


def float_quantize(x: jnp.ndarray, exp: int, man: int,
                   rounding: str = "nearest", key=None) -> jnp.ndarray:
    """Quantize an FP32 array into the eXmY format.

    Mirrors reference `float_quantize` (quant_function.py:60-75); argument
    order (exp, man) preserved.  Works on any shape, any backend (the
    reference raises NotImplementedError on CPU, quant_function.py:28-29 —
    here XLA compiles the same code for CPU/TPU).

    `rounding` selects the significand rounding:
    - ``"nearest"`` (default): round-to-nearest-even, bit-exact to the
      reference CUDA kernel.
    - ``"stochastic"`` (beyond-reference): unbiased stochastic rounding
      driven by the required PRNG `key` — the standard companion to RTNE
      for low-precision weight updates (avoids update stagnation when
      |update| < ulp/2).  All non-rounding semantics are identical.
    """
    if _validate_rounding(rounding, key):
        return cast_to_format_sr(x, exp, man, key)
    return cast_to_format(x, exp, man)


def quantizer(forward_exp: int = 8, forward_man: int = 23,
              backward_exp: int = 8, backward_man: int = 23):
    """Factory returning a function that quantizes activations on the forward
    pass and cotangents on the backward pass, with identity shortcuts when
    the format is (8, 23) — reference quant_function.py:33-57."""

    @jax.custom_vjp
    def _round(x):
        if forward_exp == 8 and forward_man == 23:
            return x
        return cast_to_format(x, forward_exp, forward_man)

    def _round_fwd(x):
        return _round(x), None

    def _round_bwd(_, g):
        if backward_exp == 8 and backward_man == 23:
            return (g,)
        return (cast_to_format(g, backward_exp, backward_man),)

    _round.defvjp(_round_fwd, _round_bwd)
    return _round


def quantizer_sr(forward_exp: int = 8, forward_man: int = 23,
                 backward_exp: int = 8, backward_man: int = 23):
    """Stochastic-rounding `quantizer` (beyond-reference): returns
    ``fn(x, key_data)`` where `key_data` is raw uint32 PRNG key data
    (`jax.random.key_data`) — activations SR-cast on forward (site 0),
    cotangents on backward (site 1), independent subkeys.  The (8, 23)
    shortcuts match `quantizer` (SR at fp32 is the identity anyway)."""

    @jax.custom_vjp
    def _round(x, key_data):
        if forward_exp == 8 and forward_man == 23:
            return x
        return cast_to_format_sr(x, forward_exp, forward_man,
                                 _site_key(key_data, 0))

    def _round_fwd(x, key_data):
        return _round(x, key_data), key_data

    def _round_bwd(key_data, g):
        if backward_exp == 8 and backward_man == 23:
            return (g, None)
        return (cast_to_format_sr(g, backward_exp, backward_man,
                                  _site_key(key_data, 1)), None)

    _round.defvjp(_round_fwd, _round_bwd)
    return _round


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def quant_gemm(a: jnp.ndarray, b: jnp.ndarray, man: int = 23, exp: int = 8,
               mode: str = "faithful", rounding: str = "nearest",
               key=None) -> jnp.ndarray:
    """GEMM ``a @ b`` with an eXmY accumulator.

    a: (M, K), b: (K, N) — reference quant_function.py:78-98.  The faithful
    mode reproduces the CUDA kernel's numerics exactly (float_kernel.cu:
    174-205): for k = 0..K-1 in order, with Kahan compensation, every
    intermediate re-cast to eXmY:

        tmp = cast(a[:, k] * b[k, :])
        y   = cast(tmp - c)
        t   = cast(s + y)
        c   = cast(cast(t - s) - y)
        s   = t

    The CUDA kernel's K-tiling (rx_outer/rx_inner) visits k strictly in
    ascending order, so a flat ordered scan is bit-identical.  Note the
    reference edge-path bug (uninitialized Kahan residual for the last row
    block when M % 16 != 0, float_kernel.cu:113,298) is UB, not semantics —
    we use a zero-initialized residual everywhere, which is what the main
    path does (float_kernel.cu:120).

    rounding="stochastic" (beyond-reference, requires `key`) replaces
    every cast — the five per-K-step faithful intermediates, or the fast
    mode's output cast — with the unbiased SR cast (one independent
    bitstream per (k, site)): the accumulator analog of the SR gradient
    pipeline, for emulating stochastic-rounding accumulators.
    """
    if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
        raise ValueError(f"quant_gemm expects (M,K)x(K,N); got {a.shape} x {b.shape}")
    sr = _validate_rounding(rounding, key)
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)

    if mode == "fast":
        # True fp32 MXU dot (HIGHEST forces fp32 multiply passes on TPU,
        # where the default would be bf16) followed by one output cast.
        out = jnp.dot(a, b, precision=lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)
        if exp == 8 and man == 23:
            return out
        if sr:
            return cast_to_format_sr(out, exp, man, key)
        return cast_to_format(out, exp, man)
    if mode != "faithful":
        raise ValueError(f"unknown quant_gemm mode: {mode!r}")
    # NOTE: no (8,23) shortcut here — the reference CUDA kernel runs the
    # Kahan-compensated sequential loop for every format including fp32
    # (quant_function.py:78-98 has no shortcut), and cast_to_format(8,23)
    # still flushes fp32-subnormal intermediates, so bit-parity requires
    # the full scan.  Use mode="fast" when emulation is not needed.

    M, K = a.shape
    N = b.shape[1]

    def step(carry, ab_k):
        s, c = carry
        a_k, b_k, i = ab_k  # (M,), (N,), scalar k index
        if sr:
            kk = jax.random.fold_in(key, i)  # one hash per K step

            def q(t, site):
                return cast_to_format_sr(t, exp, man,
                                         jax.random.fold_in(kk, site))
        else:
            def q(t, site):
                return cast_to_format(t, exp, man)
        tmp = q(a_k[:, None] * b_k[None, :], 0)
        y = q(tmp - c, 1)
        t = q(s + y, 2)
        c = q(q(t - s, 3) - y, 4)
        return (t, c), None

    init = (jnp.zeros((M, N), jnp.float32), jnp.zeros((M, N), jnp.float32))
    (s, _), _ = lax.scan(step, init, (a.T, b, jnp.arange(K)))
    return s
