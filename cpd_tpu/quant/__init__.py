from .numerics import (cast_to_format, cast_to_format_sr, cast_oracle,
                       cast_oracle_sr, max_finite, pack_exmy, unpack_exmy,
                       wire_bytes)
from .quant_function import float_quantize, quantizer, quantizer_sr, quant_gemm
from .quant_module import Quantizer, QuantDense, QuantLinear, QuantConv

__all__ = [
    "cast_to_format",
    "cast_to_format_sr",
    "cast_oracle",
    "cast_oracle_sr",
    "max_finite",
    "pack_exmy",
    "unpack_exmy",
    "wire_bytes",
    "float_quantize",
    "quantizer",
    "quantizer_sr",
    "quant_gemm",
    "Quantizer",
    "QuantDense",
    "QuantLinear",
    "QuantConv",
]
