from .numerics import cast_to_format, cast_oracle, max_finite
from .quant_function import float_quantize, quantizer, quant_gemm
from .quant_module import Quantizer, QuantDense, QuantLinear, QuantConv

__all__ = [
    "cast_to_format",
    "cast_oracle",
    "max_finite",
    "float_quantize",
    "quantizer",
    "quant_gemm",
    "Quantizer",
    "QuantDense",
    "QuantLinear",
    "QuantConv",
]
