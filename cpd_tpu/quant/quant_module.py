"""Quantized NN layers (L1): QuantLinear / QuantConv as Flax modules.

TPU-native re-implementation of reference CPDtorch/quant/quant_module.py.
The reference wires a torch autograd Function whose backward recomputes both
gradient GEMMs with the quantized accumulator and quantizes the bias-grad
sum (quant_module.py:36-52); here that recipe is a `jax.custom_vjp` around
the forward GEMM, so it composes with arbitrary surrounding autodiff (e.g.
the im2col patch extraction in QuantConv).

Weight layout parity: QuantLinear stores weight as (out_features,
in_features) like torch.nn.Linear (quant_module.py:63); QuantConv stores
(out_channels, in_channels/groups, kh, kw) (quant_module.py:92-93).
Square kernels only, like the reference; unlike the reference — which
accepts dilation/groups but silently ignores them (quant_module.py:89-90)
— both are implemented with torch semantics.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from .quant_function import (_site_key, float_quantize,
                             qgemm, quantizer, quantizer_sr)

__all__ = ["Quantizer", "QuantLinear", "QuantConv", "QuantDense",
           "quant_linear_fn"]


def _gemm(a, b, exp, man, mode, key_data, site):
    if key_data is None:
        return qgemm(a, b, exp=exp, man=man, mode=mode)
    return qgemm(a, b, exp=exp, man=man, mode=mode,
                 rounding="stochastic", key=_site_key(key_data, site))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def quant_linear_fn(x: jnp.ndarray, weight: jnp.ndarray,
                    bias: Optional[jnp.ndarray], exp: int, man: int,
                    mode: str = "faithful", key_data=None) -> jnp.ndarray:
    """y = x @ W^T + b with eXmY-accumulator GEMMs, reference backward recipe.

    x: (M, in), weight: (out, in), bias: (out,) or None.
    Forward: quant_gemm(x, W^T) + b      (quant_module.py:30-33)
    Backward: grad_x = quant_gemm(g, W); grad_W = quant_gemm(g^T, x);
              grad_b = float_quantize(g.sum(0))   (quant_module.py:36-52)

    `key_data` (beyond-reference): raw uint32 PRNG key data
    (`jax.random.key_data`); when given, every GEMM accumulator cast and
    the bias-grad cast use stochastic rounding, one independent subkey per
    site.  Passed as key DATA (a traced non-float array, cotangent None)
    rather than a typed key so it can ride the custom_vjp as a regular
    argument.
    """
    out = _gemm(x, weight.T, exp, man, mode, key_data, 0)
    if bias is not None:
        out = out + bias[None, :]
    return out


def _qlin_fwd(x, weight, bias, exp, man, mode, key_data=None):
    return (quant_linear_fn(x, weight, bias, exp, man, mode, key_data),
            (x, weight, bias, key_data))


def _qlin_bwd(exp, man, mode, res, g):
    x, weight, bias, key_data = res
    grad_x = _gemm(g, weight, exp, man, mode, key_data, 1)
    grad_w = _gemm(g.T, x, exp, man, mode, key_data, 2)
    if bias is None:
        grad_b = None
    elif key_data is None:
        grad_b = float_quantize(g.sum(0), exp, man)
    else:
        grad_b = float_quantize(g.sum(0), exp, man, rounding="stochastic",
                                key=_site_key(key_data, 3))
    return grad_x, grad_w, grad_b, None  # no cotangent for the key data


quant_linear_fn.defvjp(_qlin_fwd, _qlin_bwd)


def _kaiming_uniform(key, shape, fan_in, dtype=jnp.float32):
    # torch kaiming_uniform_(a=sqrt(5)) => bound = sqrt(6/((1+5)*fan_in))
    #                                            = 1/sqrt(fan_in)
    # (quant_module.py:71,109)
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, -bound, bound)



def _rng_key_data(module: nn.Module, rounding: str):
    """None for RTNE; raw key data from the module's 'sr' rng stream for
    stochastic rounding (callers supply rngs={'sr': key} to init/apply —
    flax raises a loud InvalidRngError otherwise)."""
    if rounding == "nearest":
        return None
    if rounding != "stochastic":
        raise ValueError(f"unknown rounding mode: {rounding!r}")
    return jax.random.key_data(module.make_rng("sr"))


class Quantizer(nn.Module):
    """Activation quantizer module (quant_module.py:13-20).

    rounding='stochastic' uses `quantizer_sr` with a key from the 'sr'
    rng stream: activations SR-cast forward, cotangents backward."""
    forward_exp: int = 8
    forward_man: int = 23
    backward_exp: int = 8
    backward_man: int = 23
    rounding: str = "nearest"

    @nn.compact
    def __call__(self, x):
        key_data = _rng_key_data(self, self.rounding)
        if key_data is None:
            return quantizer(self.forward_exp, self.forward_man,
                             self.backward_exp, self.backward_man)(x)
        return quantizer_sr(self.forward_exp, self.forward_man,
                            self.backward_exp, self.backward_man)(x, key_data)


class QuantLinear(nn.Module):
    """Linear layer with eXmY-accumulator GEMM (quant_module.py:55-85)."""
    in_features: int
    out_features: int
    use_bias: bool = True
    exp: int = 8
    man: int = 23
    mode: str = "faithful"
    rounding: str = "nearest"

    @nn.compact
    def __call__(self, x):
        weight = self.param(
            "weight",
            lambda k, s: _kaiming_uniform(k, s, self.in_features),
            (self.out_features, self.in_features))
        bias = None
        if self.use_bias:
            bias = self.param(
                "bias",
                lambda k, s: _kaiming_uniform(k, s, self.in_features),
                (self.out_features,))
        squeeze = x.ndim == 1
        x2 = x[None, :] if squeeze else x.reshape(-1, x.shape[-1])
        y = quant_linear_fn(x2, weight, bias, self.exp, self.man, self.mode,
                            _rng_key_data(self, self.rounding))
        y = y.reshape(*x.shape[:-1], self.out_features) if not squeeze else y[0]
        return y


class QuantDense(nn.Module):
    """Drop-in nn.Dense with the eXmY-accumulator GEMM.

    Unlike `QuantLinear` (torch API parity: (out, in) "weight",
    kaiming-uniform), this keeps flax's Dense contract — param named
    "kernel", shape (in, out), lecun-normal init — so it substitutes for
    nn.Dense inside existing models WITHOUT changing checkpoint layout or
    the tp PartitionSpec rules keyed on Dense kernels (e.g. the
    transformer's wi/wo_mlp, models/transformer.py).  Forward/backward
    run the same reference custom_vjp recipe as QuantLinear
    (quant_module.py:30-52); under tensor parallelism the quantized
    accumulation is per-shard with an fp32 psum on top, which changes
    rounding exactly the way the reference's per-rank dp reduction does.
    """
    features: int
    use_bias: bool = False
    exp: int = 8
    man: int = 23
    mode: str = "faithful"
    rounding: str = "nearest"
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features), self.param_dtype)
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.features,), self.param_dtype)
                if self.use_bias else None)
        x2 = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
        y = quant_linear_fn(x2, kernel.astype(jnp.float32).T, bias,
                            self.exp, self.man, self.mode,
                            _rng_key_data(self, self.rounding))
        return y.reshape(*x.shape[:-1], self.features)


class QuantConv(nn.Module):
    """2-D convolution via im2col + quantized GEMM (quant_module.py:88-139).

    NCHW layout for API parity with the reference.  Square kernels only.
    Deviation (documented, strictly better): the reference ACCEPTS
    `dilation`/`groups` but silently computes a dense dilation-1 conv
    (quant_module.py:89-90); here both are implemented — dilated patch
    extraction, and grouped conv as one quantized GEMM per group over the
    group's contiguous im2col columns (torch semantics, incl. the
    in_channels/groups fan-in for kaiming init).
    """
    in_channels: int
    out_channels: int
    kernel_size: int
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    groups: int = 1
    use_bias: bool = True
    exp: int = 8
    man: int = 23
    mode: str = "faithful"
    rounding: str = "nearest"

    @nn.compact
    def __call__(self, x):
        g = self.groups
        if self.in_channels % g or self.out_channels % g:
            raise ValueError(
                f"groups={g} must divide in_channels={self.in_channels} "
                f"and out_channels={self.out_channels}")
        k = self.kernel_size
        c_g = self.in_channels // g
        o_g = self.out_channels // g
        fan_in = c_g * k * k                 # torch fan-in under groups
        weight = self.param(
            "weight",
            lambda kk, s: _kaiming_uniform(kk, s, fan_in),
            (self.out_channels, c_g, k, k))
        bias = None
        if self.use_bias:
            bias = self.param(
                "bias",
                lambda kk, s: _kaiming_uniform(kk, s, fan_in),
                (self.out_channels,))

        b, c, h, w = x.shape
        d = self.dilation
        span = d * (k - 1) + 1               # dilated receptive field
        out_h = (h + 2 * self.padding - span) // self.stride + 1
        out_w = (w + 2 * self.padding - span) // self.stride + 1

        # im2col matching torch.nn.functional.unfold's (C, kh, kw)-major
        # patch layout (quant_module.py:135-136); rhs_dilation dilates the
        # sampling grid exactly as unfold's `dilation`.
        patches = lax.conv_general_dilated_patches(
            x,
            filter_shape=(k, k),
            window_strides=(self.stride, self.stride),
            padding=[(self.padding, self.padding)] * 2,
            rhs_dilation=(d, d),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )  # (B, C*k*k, out_h, out_w)
        patches = patches.reshape(b, c * k * k, out_h * out_w)
        patches = jnp.transpose(patches, (0, 2, 1)).reshape(b * out_h * out_w,
                                                            c * k * k)
        # per-group GEMM over the group's contiguous im2col columns (the
        # feature dim is channel-major, so group channels are adjacent)
        kd = _rng_key_data(self, self.rounding)
        outs = []
        for gi in range(g):
            cols = patches[:, gi * c_g * k * k:(gi + 1) * c_g * k * k]
            w2 = weight[gi * o_g:(gi + 1) * o_g].reshape(o_g, c_g * k * k)
            b2 = None if bias is None else bias[gi * o_g:(gi + 1) * o_g]
            kd_g = (None if kd is None
                    else jax.random.key_data(_site_key(kd, gi)))
            outs.append(quant_linear_fn(cols, w2, b2, self.exp, self.man,
                                        self.mode, kd_g))
        y = outs[0] if g == 1 else jnp.concatenate(outs, axis=-1)
        y = y.reshape(b, out_h * out_w, self.out_channels)
        y = jnp.transpose(y, (0, 2, 1))
        return y.reshape(b, self.out_channels, out_h, out_w)
