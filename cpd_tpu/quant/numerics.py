"""Core eXmY custom-precision cast — the semantic heart of the framework.

This module re-implements, TPU-natively (pure jnp bit-twiddling, fully
vectorized, jit/vmap/grad-safe), the semantics of the reference CUDA device
function ``cast_precision`` (reference: CPDtorch/quant/quant_cuda/
float_kernel.cu:10-92).  Everything else in the framework — elementwise
quantization, the quantized-accumulator GEMM, the APS low-precision gradient
all-reduce — composes this one function.

Semantics (matching the reference exactly, with deviations documented):

* Input is IEEE FP32.  Target format has ``exp_bits`` exponent bits
  (1..8) and ``man_bits`` mantissa bits (0..23), bias ``2^(exp_bits-1)-1``.
* Inf / NaN / ±0 pass through unchanged (float_kernel.cu:17-19).
* FP32 subnormal inputs flush to +0.0 — unsigned, as the reference returns
  literal ``0`` (float_kernel.cu:87-91).
* Exponent overflow is checked *before* mantissa rounding and saturates to
  ±FP32-infinity (float_kernel.cu:24-30).  Consequently a value whose
  mantissa *rounds up* past the target max does NOT become Inf — the carry
  propagates into the exponent and the (out-of-format) value ``2^(e+1)`` is
  returned, exactly as the reference does (the TODO at float_kernel.cu:71
  acknowledges this).  We replicate it bit-for-bit: emulation fidelity
  trumps IEEE correctness.
* Normal targets: round-to-nearest-even on the 24-bit significand at bit
  position ``23 - man_bits`` (float_kernel.cu:33-49).
* Subnormal targets: the significand is right-shifted by ``1 - e_new``
  first (truncating the shifted-out bits — a deliberate double-rounding
  quirk of the reference, float_kernel.cu:52) and *then* RTNE-rounded at the
  same bit position (float_kernel.cu:56-69).  We replicate the truncating
  shift exactly.
* Deviation 1: for ``man_bits == 23`` the reference's subnormal rounding
  computes ``1 << -1`` (undefined behaviour in C).  We define it as "no
  rounding" (pure truncating shift), consistent with the normal-path
  short-circuit at float_kernel.cu:33.
* Deviation 2: shifts ≥ 32 are UB in C; we define them to produce 0 (which
  is what NVIDIA hardware funnel-shifts produce in practice).

The JAX implementation is pure: it returns a new array and never aliases its
input.  The reference kernel mutates its (contiguous) input in place
(float_kernel.cu:98, quant.cu:22-23); callers that relied on that aliasing
are rewritten functionally at the API layer (quant_function.py here).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["cast_to_format", "cast_body", "cast_oracle", "max_finite",
           "cast_body_sr", "cast_to_format_sr", "cast_oracle_sr",
           "sr_bits_at", "cast_to_format_sr_at",
           "pack_exmy", "unpack_exmy", "pack_code", "unpack_code",
           "wire_bytes", "kv_page_bytes",
           "block_shifts", "cast_body_blocked", "cast_to_format_blocked",
           "pack_exmy_blocked", "unpack_exmy_blocked", "sidecar_bytes",
           "wire_bytes_blocked", "format_max_exponent",
           "quant_health", "cast_to_format_stats", "HEALTH_FIELDS",
           "FP32_EXP_BITS", "FP32_MAN_BITS"]

FP32_EXP_BITS = 8
FP32_MAN_BITS = 23


def _validate(exp_bits: int, man_bits: int) -> None:
    if not (1 <= exp_bits <= 8):
        raise ValueError(f"exp_bits must be in [1, 8], got {exp_bits}")
    if not (0 <= man_bits <= 23):
        raise ValueError(f"man_bits must be in [0, 23], got {man_bits}")


def max_finite(exp_bits: int, man_bits: int) -> float:
    """Largest value the (exp_bits, man_bits) format can represent *normally*.

    Note the reference saturates on pre-rounding exponent overflow, so the
    max *exponent field* is ``2^exp_bits - 2`` (all-ones is treated as
    reserved, float_kernel.cu:24).
    """
    _validate(exp_bits, man_bits)
    bias = (1 << (exp_bits - 1)) - 1
    e_max = ((1 << exp_bits) - 2) - bias
    sig = 2.0 - 2.0 ** (-man_bits)
    return sig * (2.0 ** e_max)


def _rtne(man: jnp.ndarray, shift: int) -> jnp.ndarray:
    """Round-to-nearest-even of an integer significand at bit `shift`.

    Mirrors the three-way branch of float_kernel.cu:33-49 / :56-69:
    round-down when the round bit is 0; round-up when the round bit is 1 and
    sticky != 0; ties resolved to even (the kept LSB).
    """
    if shift <= 0:
        return man
    half = 1 << (shift - 1)
    sticky_mask = half - 1
    keep_mask = ~((1 << shift) - 1)
    round_bit = (man & half) != 0
    sticky = (man & sticky_mask) != 0
    lsb = (man & (1 << shift)) != 0
    inc = round_bit & (sticky | lsb)
    man = jnp.where(inc, man + half, man)
    return man & keep_mask


def _pow2(e: jnp.ndarray) -> jnp.ndarray:
    """Exact fp32 power of two for integer e in [-126, 127], built by bit
    assembly (no transcendental, Mosaic/Pallas-safe)."""
    return jax.lax.bitcast_convert_type(
        ((e + 127) << 23).astype(jnp.uint32), jnp.float32)


def _cast_core(x: jnp.ndarray, exp_bits: int, man_bits: int,
               round_fn) -> jnp.ndarray:
    """Shared cast skeleton: everything except the significand rounding step.

    `round_fn(man)` maps an integer significand to its rounded value at bit
    position ``23 - man_bits`` (already masked).  `cast_body` instantiates it
    with RTNE (`_rtne`) for reference bit-parity; `cast_body_sr` with
    stochastic add-then-truncate.  The case split, saturation, subnormal
    pre-shift and value reconstruction are identical in both."""
    _validate(exp_bits, man_bits)
    x = jnp.asarray(x, jnp.float32)

    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    exp_f = ((bits >> 23) & 0xFF).astype(jnp.int32)
    man_f = (bits & 0x007FFFFF).astype(jnp.int32)
    negative = (bits >> 31) != 0

    # Case split (float_kernel.cu:17-20, :87-91).
    passthrough = (exp_f == 0xFF) | ((exp_f == 0) & (man_f == 0))
    flush_to_zero = (exp_f == 0) & (man_f != 0)

    bias = (1 << (exp_bits - 1)) - 1
    man24 = man_f | (1 << 23)
    new_e = exp_f - 127 + bias

    # Pre-rounding saturation to +/-FP32-Inf (float_kernel.cu:24-30).
    overflow = new_e >= ((1 << exp_bits) - 1)

    # Normal-target path (float_kernel.cu:31-50): round the 24-bit
    # significand; exponent carry from rounding flows into the value via the
    # shared reconstruction below.
    man_norm = round_fn(man24)
    e_norm = exp_f - 127  # new_e - bias

    # Subnormal-target path (float_kernel.cu:51-70): truncating right shift
    # by (1 - new_e), THEN round.  Shift >= 24 wipes the significand.
    sub_shift = jnp.clip(1 - new_e, 0, 24)  # man24 < 2^24, so >>24 == 0
    man_sub = round_fn(man24 >> sub_shift)
    e_sub = 1 - bias

    is_sub = new_e <= 0
    man_out = jnp.where(is_sub, man_sub, man_norm)
    e_out = jnp.where(is_sub, e_sub, e_norm)

    # Value reconstruction (float_kernel.cu:72-86): man * 2^(e-23), split
    # into two exact power-of-two factors so the subnormal tail (2^(e-23)
    # down to 2^-149) never rounds: a in [-126, 127] carries most of the
    # scale, b in [-23, 0] finishes it.  man_out < 2^25 is exact in fp32,
    # and each multiply is exact (results are k*2^-149 with k < 2^24, all
    # representable), so this equals the reference's iterative x2 / /2 loops
    # bit-for-bit.
    e = e_out - 23
    a = jnp.clip(e, -126, 127)
    b = e - a  # 0 in the normal range; [-23, 0) deep in the subnormal range
    mag = man_out.astype(jnp.float32) * _pow2(a) * _pow2(b)
    val = jnp.where(negative, -mag, mag)

    inf = jnp.where(negative, -jnp.inf, jnp.inf).astype(jnp.float32)
    val = jnp.where(overflow, inf, val)
    val = jnp.where(flush_to_zero, jnp.float32(0.0), val)
    return jnp.where(passthrough, x, val)


def cast_body(x: jnp.ndarray, exp_bits: int, man_bits: int) -> jnp.ndarray:
    """Un-jitted cast body using only ops Mosaic supports, so the SAME code
    is the XLA implementation (via `cast_to_format`) and the Pallas kernel
    body (ops/quantize.py).  See module docstring for semantics."""
    shift = 23 - man_bits
    return _cast_core(x, exp_bits, man_bits, lambda m: _rtne(m, shift))


def _sr(man: jnp.ndarray, shift: int, rbits: jnp.ndarray) -> jnp.ndarray:
    """Stochastic rounding of an integer significand at bit `shift`.

    Adds the low `shift` random bits to the significand and truncates: the
    result rounds up with probability exactly equal to the discarded
    fraction (unbiased over uniform `rbits`).  `shift <= 0` (man_bits == 23)
    is the identity, consistent with `_rtne` and deviation 1."""
    if shift <= 0:
        return man
    keep_mask = ~((1 << shift) - 1)
    r = (rbits & jnp.uint32((1 << shift) - 1)).astype(jnp.int32)
    return (man + r) & keep_mask


def cast_body_sr(x: jnp.ndarray, exp_bits: int, man_bits: int,
                 rbits: jnp.ndarray) -> jnp.ndarray:
    """Stochastic-rounding variant of `cast_body` (beyond-reference: the
    reference CUDA kernel is nearest-only, float_kernel.cu:33-49).

    `rbits` is a uint32 array broadcastable to `x.shape`; its low
    ``23 - man_bits`` bits decide the round direction per element.  All
    non-rounding semantics (Inf/NaN/±0 passthrough, FP32-subnormal flush,
    pre-rounding saturation, the subnormal truncating pre-shift, carry past
    the format max) are IDENTICAL to the RTNE cast — same format, different
    rounding.  Passing explicit bits (instead of a PRNG key) keeps the body
    Mosaic-safe so the XLA path and the Pallas kernel are bit-comparable."""
    shift = 23 - man_bits
    rbits = jnp.broadcast_to(jnp.asarray(rbits, jnp.uint32), jnp.shape(x))
    return _cast_core(x, exp_bits, man_bits, lambda m: _sr(m, shift, rbits))


@functools.partial(jax.jit, static_argnums=(1, 2))
def cast_to_format(x: jnp.ndarray, exp_bits: int, man_bits: int) -> jnp.ndarray:
    """Cast FP32 array values into the eXmY format, vectorized.

    Pure-functional, any shape/rank; `exp_bits`/`man_bits` are static so each
    format compiles once (reference: one CUDA kernel specialization per call,
    float_kernel.cu:94-101).
    """
    return cast_body(x, exp_bits, man_bits)


@functools.partial(jax.jit, static_argnums=(1, 2))
def cast_to_format_sr(x: jnp.ndarray, exp_bits: int, man_bits: int,
                      key: jax.Array) -> jnp.ndarray:
    """Stochastically-rounded eXmY cast driven by a JAX PRNG key.

    Unbiased: E[cast_to_format_sr(x)] == x for x in the format's normal
    range (each element rounds up with probability equal to its discarded
    significand fraction).  Deterministic given (x, key)."""
    rbits = jax.random.bits(key, jnp.shape(x), jnp.uint32)
    return cast_body_sr(x, exp_bits, man_bits, rbits)


def sr_bits_at(key: jax.Array, offsets: jnp.ndarray) -> jnp.ndarray:
    """Offset-indexed SR bitstream: uint32 bits per element as a pure
    function of (key, offset) — each element's bits come from its own
    threefry stream (`fold_in(key, offset)` then one draw), NOT from its
    position inside whatever array happens to hold it.

    This is what makes the gradient pipeline's stochastic rounding
    *layout-invariant*: the same (key, offset) pair yields the same bits
    whether the element is cast per-leaf, inside a fused bucket, or on a
    ZeRO reduce-scatter shard — so a sharded reduction reproduces the
    replicated reduction's bits exactly (parallel/zero.py), and bucketed
    vs per-leaf faithful reductions are bitwise identical
    (parallel/dist.py).  Costs ~2 threefry evaluations per element per
    cast site vs ~0.5 for a shape-based `jax.random.bits` — and the
    faithful ordered scan has W+1 cast sites, so this is NOT negligible:
    `tools/sr_overhead.py` measures the SR faithful reduction at
    7.8–12.3x the RTNE faithful reduction on the world=8 CPU mesh
    (0.2M–3.2M params; docs/PERF.md "SR faithful-path overhead").  The
    TPU ratio is expected lower (vectorized threefry vs the scan's ICI
    gather) but has not been measured — staged in the recapture
    pipeline.  Deployments that need cheap SR should use mode="fast"
    (one pre-/post-cast pair) or the Pallas SR kernel's hardware PRNG.

    `offsets` may be any shape; values must fit uint32 (documented limit:
    reductions over > 2^32 elements would need a wider fold)."""
    flat = jnp.reshape(jnp.asarray(offsets, jnp.uint32), (-1,))
    keys = jax.vmap(lambda o: jax.random.fold_in(key, o))(flat)
    bits = jax.vmap(lambda k: jax.random.bits(k, (), jnp.uint32))(keys)
    return bits.reshape(jnp.shape(offsets))


def cast_to_format_sr_at(x: jnp.ndarray, exp_bits: int, man_bits: int,
                         key: jax.Array, offsets: jnp.ndarray) -> jnp.ndarray:
    """Stochastically-rounded eXmY cast with offset-indexed bits.

    Like `cast_to_format_sr` but the per-element round bits are drawn by
    global element offset (`sr_bits_at`) instead of by position in
    `x.shape` — the layout-invariant variant the reduction pipeline uses.
    `offsets` must have x's shape (or broadcast to it)."""
    rbits = jnp.broadcast_to(sr_bits_at(key, offsets), jnp.shape(x))
    return cast_body_sr(x, exp_bits, man_bits, rbits)


# --------------------------------------------------------------------------
# Numeric-health telemetry (the precision supervisor's sensor layer,
# resilience/precision.py).
#
# A launch-time format choice is a bet about runtime value ranges; these
# counters are how a run notices the bet going bad WHILE it can still
# react.  `quant_health` observes one cast's (input, output) pair and
# counts the three failure signatures of the eXmY cast semantics above:
#
#   sat       — output is ±Inf: the pre-rounding exponent-overflow
#               saturation (float_kernel.cu:24-30) fired, or an Inf that
#               was already in the input passed through.  Either way the
#               format is carrying Inf — the health problem is the same.
#   underflow — a non-zero finite input came out exactly 0: the
#               fp32-subnormal flush (float_kernel.cu:87-91) or the
#               subnormal-target path rounding the whole significand
#               away.  Gradient mass silently vanishing.
#   nan       — NaN inputs (passthrough): poison already upstream of the
#               cast, counted here because the cast site is where a
#               format ladder can still re-trace before the optimizer
#               eats it.
#
# Pure observation: the caller hands in whatever the cast produced, so
# enabling telemetry CANNOT change the cast's bits (gated bitwise in
# tools/bench_reduce.py --smoke).  Counters are float32 scalars —
# exact for any count below 2^24, and immune to the int32 wrap that a
# pod-scale psum (n_params x world) or a faithful-GEMM scan total
# (5·K·M·N) would hit; at those magnitudes the ~1e-7 relative rounding
# is noise against the supervisor's rate threshold.  Summable across
# leaves, sites and replicas (lax.psum).
# --------------------------------------------------------------------------

HEALTH_FIELDS = ("sat", "underflow", "nan", "total")


def quant_health(x: jnp.ndarray, q: jnp.ndarray) -> dict:
    """{sat, underflow, nan, total} float32 scalars for one cast's input
    `x` and output `q` (see the block comment above for the exact
    definitions, including why float32 and not int32 — the pod-scale
    overflow).  `total` is the element count, so callers can turn sums
    into rates.

    Zero-ness is decided on the BIT PATTERN, not by a float compare:
    XLA's CPU backend compares under DAZ semantics, where an fp32
    subnormal == 0.0 — a value compare would both miss the
    subnormal-input flush (the reference's own flush case,
    float_kernel.cu:87-91) and falsely flag e8 formats' legitimate
    subnormal OUTPUTS as underflow."""
    x = jnp.asarray(x, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    mag = jnp.uint32(0x7FFFFFFF)
    x_nonzero = (jax.lax.bitcast_convert_type(x, jnp.uint32) & mag) != 0
    q_zero = (jax.lax.bitcast_convert_type(q, jnp.uint32) & mag) == 0
    f32 = jnp.float32
    return {
        "sat": jnp.sum(jnp.isinf(q).astype(f32)),
        "underflow": jnp.sum((q_zero & x_nonzero
                              & jnp.isfinite(x)).astype(f32)),
        "nan": jnp.sum(jnp.isnan(x).astype(f32)),
        "total": jnp.asarray(x.size, f32),
    }


@functools.partial(jax.jit, static_argnums=(1, 2))
def cast_to_format_stats(x: jnp.ndarray, exp_bits: int,
                         man_bits: int) -> tuple:
    """`cast_to_format` plus its health counters: ``(q, health)`` where
    ``q`` is BITWISE identical to the plain cast (same `cast_body`) and
    ``health`` is `quant_health(x, q)` (float32 scalars)."""
    q = cast_body(x, exp_bits, man_bits)
    return q, quant_health(x, q)


# --------------------------------------------------------------------------
# Bit-packed eXmY wire format (the transport codec of parallel/ring.py and
# the compressed all_gather / all_to_all wires in parallel/dist.py,
# parallel/zero.py).
#
# An fp32 value that came out of `cast_to_format(·, e, m)` carries only
# 1 + e + m bits of information: sign, the format's e-bit exponent field,
# and the m-bit mantissa field.  `pack_exmy` re-encodes each element into
# that code word, stored little-endian in ceil((1+e+m)/8) bytes, and
# `unpack_exmy` reconstructs the exact fp32 bit pattern.  This replaces the
# old 3-entry hardware-dtype table (e5m2/f16/bf16 only): ANY format with
# man_bits >= 2 now ships compressed — including (4,3), whose saturating
# cast produces ±Inf that float8_e4m3fn cannot represent.
#
# Code-word layout (bit 0 = LSB):   [ man (m) | exp (e) | sign (1) ]
#   exp field 0            → format subnormal: value = man · 2^(1-bias-m)
#   exp field 1..2^e-2     → normal: value = (2^m + man) · 2^(F-bias-m)
#   exp field all-ones     → specials, discriminated by the mantissa code:
#       man 0 → ±Inf (the cast's pre-round saturation output)
#       man 1 → ±2^(e_max+1), the carry-past-max value the reference cast
#               deliberately emits (module docstring; float_kernel.cu:71)
#       man 2 → NaN (canonicalized — payload bits are not format data)
# The three specials are why man_bits >= 2 is required: with m < 2 the
# all-ones block has too few codes.  (8,23) bypasses the codec entirely —
# the code word IS the fp32 bit pattern, so packing is a byte split and
# every NaN payload survives.
#
# Losslessness contract: for x in the (e, m) cast's OUTPUT set (any array
# that went through cast_to_format / cast_body_sr at the same format),
# unpack_exmy(pack_exmy(x)) == x bit-for-bit, including -0.0, format
# subnormals (which for e == 8 are fp32 subnormals), ±Inf and the carry
# value.  Values outside that set are a caller error (the low mantissa
# bits are truncated, out-of-range exponents best-effort to carry/Inf).
# --------------------------------------------------------------------------


def wire_bytes(exp_bits: int, man_bits: int) -> int:
    """Bytes per element of the packed eXmY wire format."""
    _validate(exp_bits, man_bits)
    return (1 + exp_bits + man_bits + 7) // 8


def kv_page_bytes(exp_bits: int, man_bits: int, page_size: int,
                  n_kv_heads: int, head_dim: int,
                  block_size=None, tp: int = 1) -> int:
    """Bytes of ONE layer's K+V KV-cache page in the packed eXmY codec.

    The analytic sibling of `wire_bytes` for the serving stack's paged
    KV cache (cpd_tpu/serve/kvcache.py): a page holds `page_size` token
    positions × `n_kv_heads` × `head_dim` elements for BOTH the K and V
    planes, each element one `wire_bytes(exp_bits, man_bits)` code word.
    Multiply by the layer count for a request's whole-model page cost.
    This is the one source of truth bench/docs quote for KV memory per
    format; tests pin it against the actual packed page-pool slice.
    Applies the full packed-wire validation (`_validate_wire`, incl.
    the man >= 2 special-code rule): a page count for a format the
    packed cache cannot store would be a lie.

    ``block_size`` prices the BLOCK-SCALED page (ISSUE 12): each K/V
    row (one token position's n_kv_heads·head_dim elements) carries its
    `sidecar_bytes` shift lane next to the code words — the sidecar is
    EXPLICIT here, and the test pins this against the real blocked pool
    slice so the analytics can never silently under-report KV memory.

    ``tp`` prices a head-group-sharded page (ISSUE 18): the row splits
    into ``tp`` shard-local rows of ``n_kv_heads // tp`` heads, each
    carrying its OWN blocked sidecar (scale blocks span the shard-local
    row, so the sharded page is not simply the tp=1 page — the sidecar
    count can differ).  The return is the whole-page engine-aggregate;
    divide the per-shard call (``tp=1`` on ``n_kv_heads // tp`` heads)
    out yourself for the shard slice."""
    if page_size < 1 or n_kv_heads < 1 or head_dim < 1:
        raise ValueError(
            f"page_size/n_kv_heads/head_dim must be >= 1, got "
            f"({page_size}, {n_kv_heads}, {head_dim})")
    if tp < 1 or n_kv_heads % tp != 0:
        raise ValueError(
            f"tp={tp} must be >= 1 and divide n_kv_heads={n_kv_heads}: "
            "pages shard by whole KV head groups")
    _validate_wire(exp_bits, man_bits)
    n = (n_kv_heads // tp) * head_dim      # shard-local row elements
    row = n * wire_bytes(exp_bits, man_bits)
    if block_size is not None:
        if exp_bits == 8 and man_bits == 23:
            raise ValueError("block_size at (8, 23): the fp32 byte split "
                             "has nothing to scale — no blocked page "
                             "exists to price")
        row += sidecar_bytes(n, block_size)
    return tp * 2 * page_size * row


def kv_pool_bytes(exp_bits: int, man_bits: int, page_size: int,
                  n_kv_heads: int, head_dim: int, *, n_layers: int,
                  logical_pages: int, shared_pages: int = 0,
                  block_size=None, tp: int = 1) -> dict:
    """Whole-pool KV accounting with prefix-cache dedup (ISSUE 13
    satellite): ``logical_pages`` page ids as the requests see them,
    of which ``shared_pages`` are copy-on-write references to a page
    another request (or the prefix cache) already holds — so they cost
    ZERO resident bytes.  A page id spans every layer (the pool is
    ``(L, n_pages, ...)``), hence the ``n_layers`` factor on
    `kv_page_bytes` (which prices ONE layer's K+V page, sidecar
    included under ``block_size``).

    Returns ``{page_bytes, logical_bytes, resident_bytes,
    saved_bytes}`` — the dedup-savings ledger the fleet bench
    (`bench_serve --fleet`) prices its prefix-hit sweep with.  Pinned
    against real pool slices in tests (like the PR 12 sidecar
    pricing): the analytics can never silently under-report KV
    memory.

    ``tp`` prices a head-group-sharded pool (ISSUE 18): all byte
    figures stay engine-aggregate (summed over shards), and the dict
    gains ``tp`` plus ``shard_page_bytes`` — one shard's whole-model
    page cost, what each shard device actually holds per page id."""
    if n_layers < 1:
        raise ValueError(f"n_layers must be >= 1, got {n_layers}")
    if logical_pages < 0 or not 0 <= shared_pages <= logical_pages:
        raise ValueError(
            f"need 0 <= shared_pages <= logical_pages, got "
            f"({shared_pages}, {logical_pages})")
    page = n_layers * kv_page_bytes(exp_bits, man_bits, page_size,
                                    n_kv_heads, head_dim,
                                    block_size=block_size, tp=tp)
    out = {"page_bytes": page,
           "logical_bytes": logical_pages * page,
           "resident_bytes": (logical_pages - shared_pages) * page,
           "saved_bytes": shared_pages * page}
    if tp > 1:
        out["tp"] = tp
        out["shard_page_bytes"] = n_layers * kv_page_bytes(
            exp_bits, man_bits, page_size, n_kv_heads // tp, head_dim,
            block_size=block_size)
    return out


def _validate_wire(exp_bits: int, man_bits: int) -> None:
    _validate(exp_bits, man_bits)
    if man_bits < 2 and not (exp_bits == 8 and man_bits == 23):
        raise ValueError(
            f"pack_exmy needs man_bits >= 2 (got ({exp_bits}, {man_bits})): "
            "the all-ones exponent block must hold the Inf/carry/NaN "
            "special codes; ship such formats as raw fp32 instead")


def _split_bytes(code: jnp.ndarray, n_bytes: int) -> jnp.ndarray:
    """uint32 code words -> little-endian uint8 array, one trailing axis."""
    return jnp.stack(
        [((code >> (8 * k)) & jnp.uint32(0xFF)).astype(jnp.uint8)
         for k in range(n_bytes)], axis=-1)


def _join_bytes(packed: jnp.ndarray) -> jnp.ndarray:
    """Little-endian uint8 (..., B) -> uint32 code words (...)."""
    code = jnp.zeros(packed.shape[:-1], jnp.uint32)
    for k in range(packed.shape[-1]):
        code = code | (packed[..., k].astype(jnp.uint32) << (8 * k))
    return code


def pack_code(x: jnp.ndarray, exp_bits: int, man_bits: int) -> jnp.ndarray:
    """Un-jitted pack body: fp32 values in the (exp_bits, man_bits) value
    set -> uint32 code words.  Pure bit arithmetic on ops Mosaic
    supports, so the SAME code is the XLA packer (`pack_exmy`) and the
    fused Pallas wire kernel's pack stage (ops/quantize.py) — the
    `cast_body` pattern applied to the codec."""
    _validate_wire(exp_bits, man_bits)
    x = jnp.asarray(x, jnp.float32)
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    if exp_bits == 8 and man_bits == 23:
        return bits

    sign = (bits >> 31) & jnp.uint32(1)
    exp_f = ((bits >> 23) & jnp.uint32(0xFF)).astype(jnp.int32)
    man_f = (bits & jnp.uint32(0x007FFFFF)).astype(jnp.int32)
    bias = (1 << (exp_bits - 1)) - 1
    ones = (1 << exp_bits) - 1

    is_nan = (exp_f == 0xFF) & (man_f != 0)
    is_inf = (exp_f == 0xFF) & (man_f == 0)
    # fp32 subnormal inputs have no implicit bit and a fixed 2^-126 scale
    man24 = jnp.where(exp_f > 0, man_f | (1 << 23), man_f)
    f = jnp.where(exp_f > 0, exp_f - 127, -126) + bias

    # format-subnormal when the value sits below the format's normal range
    # OR the fp32 pattern itself is subnormal (e == 8 formats)
    is_sub = (f <= 0) | (exp_f == 0)
    # finite exponent at/above the all-ones field: the carry value
    is_carry = (~is_sub) & (exp_f != 0xFF) & (f >= ones)

    shift = jnp.clip(jnp.maximum(1 - f, 0) + (23 - man_bits), 0, 31)
    man_sub = man24 >> shift
    man_norm = man_f >> (23 - man_bits)

    exp_field = jnp.where(is_sub, 0, jnp.clip(f, 0, ones)).astype(jnp.uint32)
    man_field = jnp.where(is_sub, man_sub, man_norm).astype(jnp.uint32)
    code = (sign << (exp_bits + man_bits)) | (exp_field << man_bits) \
        | man_field
    # specials: all-ones exponent + discriminant code
    top = jnp.uint32(ones << man_bits)
    code = jnp.where(is_carry, (sign << (exp_bits + man_bits)) | top
                     | jnp.uint32(1), code)
    code = jnp.where(is_inf, (sign << (exp_bits + man_bits)) | top, code)
    code = jnp.where(is_nan, top | jnp.uint32(2), code)
    return code


@functools.partial(jax.jit, static_argnums=(1, 2))
def pack_exmy(x: jnp.ndarray, exp_bits: int, man_bits: int) -> jnp.ndarray:
    """Pack fp32 values already in the (exp_bits, man_bits) value set into
    little-endian uint8 code words of shape ``x.shape + (wire_bytes(),)``."""
    return _split_bytes(pack_code(x, exp_bits, man_bits),
                        wire_bytes(exp_bits, man_bits))


def unpack_code(code: jnp.ndarray, exp_bits: int,
                man_bits: int) -> jnp.ndarray:
    """Un-jitted unpack body: uint32 code words -> the exact fp32 bit
    patterns the cast produced.  Mosaic-safe twin of `pack_code` (see
    its docstring); `unpack_exmy` and the fused hop kernel share it."""
    _validate_wire(exp_bits, man_bits)
    code = jnp.asarray(code, jnp.uint32)
    if exp_bits == 8 and man_bits == 23:
        return jax.lax.bitcast_convert_type(code, jnp.float32)

    bias = (1 << (exp_bits - 1)) - 1
    ones = (1 << exp_bits) - 1
    sign = ((code >> (exp_bits + man_bits)) & jnp.uint32(1)) != 0
    exp_field = ((code >> man_bits) & jnp.uint32(ones)).astype(jnp.int32)
    man_field = (code & jnp.uint32((1 << man_bits) - 1)).astype(jnp.int32)

    is_special = exp_field == ones
    is_sub = exp_field == 0
    # normals: (2^m + man) * 2^(F - bias - m); subnormals: man * 2^(1-bias-m)
    mantissa = jnp.where(is_sub, man_field, man_field | (1 << man_bits))
    e = jnp.where(is_sub, 1, exp_field) - bias - man_bits
    # carry special: 1 * 2^(e_max + 1); e_max + 1 = ones - bias.  For e == 8
    # that is 2^128, which the exact pow2 product below overflows to +Inf —
    # the same value the e == 8 cast itself produces in place of a carry.
    is_carry = is_special & (man_field == 1)
    mantissa = jnp.where(is_carry, 1, mantissa)
    e = jnp.where(is_carry, ones - bias, e)
    # exact two-factor power-of-two product (see _cast_core's reconstruction)
    a = jnp.clip(e, -126, 127)
    b = jnp.clip(e - a, -126, 127)
    mag = mantissa.astype(jnp.float32) * _pow2(a) * _pow2(b)
    inf = jnp.float32(jnp.inf)
    mag = jnp.where(is_special & (man_field == 0), inf, mag)
    val = jnp.where(sign, -mag, mag)
    return jnp.where(is_special & (man_field >= 2), jnp.float32(jnp.nan),
                     val)


@functools.partial(jax.jit, static_argnums=(1, 2))
def unpack_exmy(packed: jnp.ndarray, exp_bits: int,
                man_bits: int) -> jnp.ndarray:
    """Inverse of `pack_exmy`: uint8 ``(..., wire_bytes())`` -> fp32 ``(...)``
    with the exact bit patterns the cast produced."""
    n_bytes = wire_bytes(exp_bits, man_bits)
    packed = jnp.asarray(packed, jnp.uint8)
    if packed.shape[-1] != n_bytes:
        raise ValueError(f"trailing axis {packed.shape[-1]} != "
                         f"wire_bytes({exp_bits}, {man_bits}) = {n_bytes}")
    return unpack_code(_join_bytes(packed), exp_bits, man_bits)


# --------------------------------------------------------------------------
# Block-scaled eXmY codec (EQuARX-style, PAPERS.md #2; the ring transport's
# `block_scale=` wire, parallel/ring.py).
#
# APS (parallel/aps.py) shifts exponents per-TENSOR: one shared scale for
# every element of a leaf, chosen from the global max.  A tensor whose
# blocks span very different magnitudes then wastes the format's dynamic
# range everywhere except near the max — small-magnitude regions flush.
# Block scaling shares one power-of-2 scale per BLOCK of `block_size`
# consecutive elements instead: each block's values are scaled so its own
# max sits at the format's top normal exponent, cast to (exp, man), and
# the 1-byte shift rides the wire as a sidecar lane next to the packed
# code words.  An e4m3 code word + 1/block_size sidecar bytes then covers
# the dynamic range a per-tensor e5m7 cannot — the new accuracy/bytes
# frontier point tools/bench_reduce.py --block-sweep measures.
#
# Semantics (beyond-reference — the reference has no blocked cast):
#
#   per block b (blocks along the LAST axis; the tail block may be short):
#     E_b  = floor(log2(max finite |x| in b))     (0 if no finite nonzero)
#     k_b  = clip(E_b - emax, -128, 127)          emax = max_finite's exp
#     y    = x * 2^-k_b                           (exact power-of-2 scale)
#     q_s  = cast(y, exp, man)                    (RTNE or SR)
#     q_s  = +/-max_finite where a FINITE y rounded past the format max
#            (the reference cast's carry quirk, float_kernel.cu:71 —
#            clamped HERE so the scale derivation is a fixed point: the
#            quantized block max keeps exponent emax, so re-deriving k_b
#            from the output reproduces k_b exactly, which is what makes
#            `pack_exmy_blocked` idempotent/lossless on its output set)
#     out  = q_s * 2^k_b
#
# Inf/NaN pass through the cast and ride the codec's special codes; the
# shift derivation ignores them (a block of only specials gets k_b = 0).
# Zeros are invariant under any scale, so the ring's zero padding stays
# rounding-neutral.  EVERYTHING below the fp32 normal floor — subnormal
# inputs, -0.0, inputs whose scaled form would be subnormal, and
# unscaled results that would land there — canonicalizes to +0.0: the
# reference cast's own subnormal-input flush (float_kernel.cu:87-91)
# extended to the whole class, because XLA backends FTZ/DAZ subnormals
# inconsistently across fusion boundaries (and frexp mis-reports them),
# so any blocked semantics that DISTINGUISHED patterns inside that class
# would diverge between the distributed ring and its single-device
# oracle.  With the class flushed, every surviving multiply is an exact
# normal-range product and the codec round-trip is idempotent.
#
# Sidecar lane: one uint8 per block, value k_b + 128.  Wire layout of
# `pack_exmy_blocked` (last axis): [ n * wire_bytes code bytes | n_blocks
# sidecar bytes ] — one flat uint8 lane per payload, so the ring's hop
# digest covers codes AND scales in a single pass.
# --------------------------------------------------------------------------


def format_max_exponent(exp_bits: int) -> int:
    """Exponent of `max_finite(exp_bits, ·)`: (2^e - 2) - bias."""
    _validate(exp_bits, 0)
    return ((1 << exp_bits) - 2) - ((1 << (exp_bits - 1)) - 1)


def _scale_pow2(x: jnp.ndarray, e: jnp.ndarray) -> jnp.ndarray:
    """x * 2^e for integer e in [-252, 253], applied as two sequential
    exact power-of-two factors (NEVER as a precomputed 2^e scalar, which
    for |e| > 127 would itself overflow/flush and poison the product).
    Each factor multiply is exact unless the running result crosses the
    fp32 subnormal floor or overflows — deterministic either way."""
    a = jnp.clip(e, -126, 127)
    return (x * _pow2(a)) * _pow2(jnp.clip(e - a, -126, 126))


def sidecar_bytes(n: int, block_size: int) -> int:
    """Sidecar-lane bytes for n elements at one shift byte per block."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    return -(-n // block_size) if n else 0


def wire_bytes_blocked(exp_bits: int, man_bits: int, n: int,
                       block_size: int) -> int:
    """Total wire bytes of one block-scaled payload of n elements: the
    packed code words plus the sidecar lane.  The analytic twin of
    `pack_exmy_blocked`'s output size (pinned against the real buffer
    in tests)."""
    _validate_wire(exp_bits, man_bits)
    return n * wire_bytes(exp_bits, man_bits) + sidecar_bytes(n, block_size)


def _flush_low(x: jnp.ndarray) -> jnp.ndarray:
    """Canonicalize the entire sub-normal-floor class — fp32 subnormals
    AND ±0.0 — to +0.0.  XLA backends FTZ/DAZ subnormals inconsistently
    across fusion boundaries (a subnormal intermediate may reach the
    next op as ±tiny in one program and as ∓0.0 in another), and frexp
    mis-reports them outright — so the blocked pipeline flushes the
    whole CLASS up front: every pattern with a zero exponent field maps
    to the same +0.0 no matter which form the backend delivered."""
    bits = jax.lax.bitcast_convert_type(jnp.asarray(x, jnp.float32),
                                        jnp.uint32)
    low = ((bits >> 23) & jnp.uint32(0xFF)) == 0
    return jnp.where(low, jnp.float32(0.0), x)


def block_shifts(x: jnp.ndarray, exp_bits: int, man_bits: int,
                 block_size: int) -> jnp.ndarray:
    """Per-block power-of-2 shift exponents k_b (int32), blocks of
    `block_size` along the LAST axis (short tail block included).
    Shape: x.shape[:-1] + (ceil(n / block_size),).  Sub-2^-126 inputs
    count as zero (`_flush_low` — the blocked cast flushes them)."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    x = _flush_low(jnp.asarray(x, jnp.float32))
    n = x.shape[-1]
    nb = sidecar_bytes(n, block_size)
    mag = jnp.where(jnp.isfinite(x), jnp.abs(x), 0.0)
    pad = nb * block_size - n
    if pad:
        mag = jnp.pad(mag, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    m_b = jnp.max(mag.reshape(x.shape[:-1] + (nb, block_size)), axis=-1)
    # floor(log2(m)) via frexp (exact on normals): m = f * 2^e, f in
    # [0.5, 1)
    _, e = jnp.frexp(m_b)
    emax = format_max_exponent(exp_bits)
    k = jnp.where(m_b > 0, e.astype(jnp.int32) - 1 - emax, 0)
    return jnp.clip(k, -128, 127)


def _per_element_shifts(shifts: jnp.ndarray, n: int,
                        block_size: int) -> jnp.ndarray:
    """Broadcast (..., nb) block shifts to (..., n) element shifts."""
    rep = jnp.repeat(shifts, block_size, axis=-1)
    return rep[..., :n]


def _unscale_flush(q: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """q * 2^k with would-be-fp32-subnormal results flushed to +0.0 (the
    blocked cast's output flush — see the block comment; shared by the
    cast and the unpacker so both reconstruct identical bits).

    The flush condition is decided from (q, k) EXPONENT arithmetic, not
    from the product's bit pattern: XLA backends disagree about whether
    a subnormal product survives a fusion boundary (CPU FTZ), so a
    pattern test would flush on one path and miss on another — a ±0.0
    divergence between the distributed ring and its oracle.  With
    frexp(|q|) = f · 2^e (f in [0.5, 1)), |q · 2^k| < 2^-126 iff
    e + k <= -126; everything kept is then a NORMAL product of exactly
    representable factors — exact on every backend."""
    _, e = jnp.frexp(q)
    flush = (jnp.isfinite(q) & (q != 0)
             & (e.astype(jnp.int32) + k <= -126))
    out = jnp.where(flush, jnp.float32(0.0), _scale_pow2(q, k))
    # the base cast's subnormal-target rounding can emit -0.0 (a wiped
    # negative significand keeps its sign); fold it into the +0.0 class
    return _flush_low(out)


def _block_quantize(x: jnp.ndarray, exp_bits: int, man_bits: int,
                    block_size: int, rbits=None) -> tuple:
    """Shared shift-scale-cast-clamp core of the blocked cast and the
    blocked packer: returns ``(q_scaled, shifts, k_elem)`` — the
    SCALED-domain quantized values (exactly what the wire's code words
    encode), the per-block shifts, and the per-element shift broadcast.
    Sub-floor inputs (and inputs whose scaled form would be fp32-
    subnormal) flush to +0.0 FIRST, so no multiply or frexp ever sees a
    pattern a backend's FTZ could have already rewritten."""
    _validate(exp_bits, man_bits)
    x = _flush_low(jnp.asarray(x, jnp.float32))
    shifts = block_shifts(x, exp_bits, man_bits, block_size)
    k = _per_element_shifts(shifts, x.shape[-1], block_size)
    _, ex = jnp.frexp(x)
    tiny = (jnp.isfinite(x) & (x != 0)
            & (ex.astype(jnp.int32) - 1 - k <= -127))
    x = jnp.where(tiny, jnp.float32(0.0), x)
    y = _scale_pow2(x, -k)
    if rbits is None:
        q = cast_body(y, exp_bits, man_bits)
    else:
        q = cast_body_sr(y, exp_bits, man_bits, rbits)
    mf = jnp.float32(max_finite(exp_bits, man_bits))
    carry = jnp.isfinite(y) & (jnp.abs(q) > mf)
    q = jnp.where(carry, jnp.where(q > 0, mf, -mf), q)
    return q, shifts, k


def cast_body_blocked(x: jnp.ndarray, exp_bits: int, man_bits: int,
                      block_size: int, rbits=None) -> jnp.ndarray:
    """Block-scaled eXmY cast (see the block comment above): per-block
    power-of-2 scale to the format's top exponent, cast (RTNE, or SR when
    `rbits` is given — same contract as `cast_body_sr`), carry clamped to
    +/-max_finite, unscale.  The ring's blocked hop quantizer AND
    `ring_oracle_sum(block_size=...)` share this one body, so the
    distributed transport and its oracle cannot drift."""
    q, _, k = _block_quantize(x, exp_bits, man_bits, block_size, rbits)
    return _unscale_flush(q, k)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def cast_to_format_blocked(x: jnp.ndarray, exp_bits: int, man_bits: int,
                           block_size: int) -> jnp.ndarray:
    """Jitted RTNE `cast_body_blocked` (blocks along the last axis)."""
    return cast_body_blocked(x, exp_bits, man_bits, block_size)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def pack_exmy_blocked(x: jnp.ndarray, exp_bits: int, man_bits: int,
                      block_size: int) -> jnp.ndarray:
    """Quantize-and-pack into the block-scaled wire: shift, RTNE-cast
    (identity when x is already in the blocked value set — SR callers
    pre-cast with `cast_body_blocked(..., rbits)` and pack losslessly),
    pack the SCALED code words, and append the sidecar lane.

    Output (last axis): ``n * wire_bytes(exp, man)`` little-endian code
    bytes followed by ``ceil(n / block_size)`` sidecar bytes (k + 128).
    Losslessness: ``unpack_exmy_blocked(pack_exmy_blocked(x)) ==
    cast_body_blocked(x)`` bitwise, and is the identity on anything that
    already went through the blocked cast at the same (format, block) —
    the fixed-point shift derivation above is what guarantees the
    re-derived k_b matches."""
    _validate_wire(exp_bits, man_bits)
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[-1]
    q, shifts, _ = _block_quantize(x, exp_bits, man_bits, block_size)
    codes = pack_exmy(q, exp_bits, man_bits)
    codes = codes.reshape(x.shape[:-1] + (n * codes.shape[-1],))
    sidecar = (shifts + 128).astype(jnp.uint8)
    return jnp.concatenate([codes, sidecar], axis=-1)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def unpack_exmy_blocked(packed: jnp.ndarray, exp_bits: int, man_bits: int,
                        n: int, block_size: int) -> jnp.ndarray:
    """Inverse of `pack_exmy_blocked`: split the sidecar lane off the
    wire, decode the scaled code words, and unscale each block by its
    ridden 2^k — reproducing the blocked cast's output bit-for-bit."""
    _validate_wire(exp_bits, man_bits)
    wb = wire_bytes(exp_bits, man_bits)
    nb = sidecar_bytes(n, block_size)
    packed = jnp.asarray(packed, jnp.uint8)
    if packed.shape[-1] != n * wb + nb:
        raise ValueError(
            f"trailing axis {packed.shape[-1]} != wire_bytes_blocked("
            f"{exp_bits}, {man_bits}, n={n}, block={block_size}) = "
            f"{n * wb + nb}")
    codes = packed[..., :n * wb].reshape(packed.shape[:-1] + (n, wb))
    shifts = packed[..., n * wb:].astype(jnp.int32) - 128
    q = unpack_exmy(codes, exp_bits, man_bits)
    k = _per_element_shifts(shifts, n, block_size)
    return _unscale_flush(q, k)


def cast_oracle_sr(x: float, exp_bits: int, man_bits: int, r: int) -> float:
    """Scalar oracle for the stochastic cast: follows `cast_oracle`'s control
    flow with RTNE replaced by add-`r`-then-truncate (r in [0, 2^shift)).
    Used by tests to pin the SR semantics independently of the jnp path."""
    _validate(exp_bits, man_bits)
    s = 23 - man_bits
    if not (0 <= r < (1 << s if s > 0 else 1)):
        raise ValueError(f"r must be in [0, 2^{max(s, 0)}), got {r}")
    f = np.float32(x)
    old_num = int(np.array(f, np.float32).view(np.uint32))
    exp = (old_num & 0x7F800000) >> 23
    man = old_num & 0x007FFFFF
    true_exp = exp - 127
    if exp == 0xFF or (exp == 0x00 and man == 0):
        return float(f)
    if exp == 0:
        return 0.0
    man = man | (1 << 23)
    diy_bias = (1 << (exp_bits - 1)) - 1
    new_e = true_exp + diy_bias
    if new_e >= (1 << exp_bits) - 1:
        return float(np.inf if f > 0 else -np.inf)
    if new_e > 0:
        if man_bits != 23:
            man = (man + r) & ~((1 << s) - 1)
        new_e -= diy_bias
    else:
        shift_amt = 1 - new_e
        man = man >> shift_amt if shift_amt < 32 else 0
        new_e = 1 - diy_bias
        if man_bits != 23:
            man = (man + r) & ~((1 << s) - 1)
    res = np.float32(man) / np.float32(1 << 23)
    if new_e >= 0:
        for _ in range(new_e):
            res = np.float32(res * np.float32(2.0))
    else:
        for _ in range(-new_e):
            res = np.float32(res / np.float32(2.0))
    if old_num & (1 << 31):
        res = -res
    return float(res)


def cast_oracle(x: float, exp_bits: int, man_bits: int) -> float:
    """Scalar NumPy transliteration of float_kernel.cu:10-92, used as the
    correctness oracle in tests.  Follows the CUDA control flow literally."""
    _validate(exp_bits, man_bits)
    f = np.float32(x)
    old_num = int(np.array(f, np.float32).view(np.uint32))
    exp = (old_num & 0x7F800000) >> 23
    man = old_num & 0x007FFFFF
    true_exp = exp - 127
    if exp == 0xFF or (exp == 0x00 and man == 0):
        return float(f)
    if exp > 0:
        man = man | (1 << 23)
        diy_bias = (1 << (exp_bits - 1)) - 1
        new_e = true_exp + diy_bias
        if new_e >= (1 << exp_bits) - 1:
            return float(np.inf if f > 0 else -np.inf)
        s = 23 - man_bits
        if new_e > 0:
            if man_bits == 23 or (man & (1 << (s - 1))) == 0:
                man = man & ~((1 << s) - 1)
            elif (man & ((1 << (s - 1)) - 1)) != 0:
                man = (man + (1 << (s - 1))) & ~((1 << s) - 1)
            else:
                if (man & (1 << s)) != 0:
                    man = man + (1 << (s - 1))
                man = man & ~((1 << s) - 1)
            new_e -= diy_bias
        else:
            shift_amt = 1 - new_e
            man = man >> shift_amt if shift_amt < 32 else 0
            new_e = 1 - diy_bias
            if man_bits == 23:  # deviation 1: defined as no rounding
                pass
            elif (man & (1 << (s - 1))) == 0:
                man = man & ~((1 << s) - 1)
            elif (man & ((1 << (s - 1)) - 1)) != 0:
                man = (man + (1 << (s - 1))) & ~((1 << s) - 1)
            else:
                if (man & (1 << s)) != 0:
                    man = man + (1 << (s - 1))
                man = man & ~((1 << s) - 1)
        res = np.float32(man) / np.float32(1 << 23)
        if new_e >= 0:
            for _ in range(new_e):
                res = np.float32(res * np.float32(2.0))
        else:
            for _ in range(-new_e):
                res = np.float32(res / np.float32(2.0))
        if old_num & (1 << 31):
            res = -res
        return float(res)
    return 0.0
